"""The train→deploy seam end to end (the KFP→object-store→KServe story):

1. publish a text dataset into the platform artifact store,
2. train() on it (the worker resolves artifact:// through the store),
3. publish the run's checkpoint as a named, versioned model artifact,
4. serve it by that name — `storage_uri="artifact://demo-model@1"` —
   with an explainer hop on the side.

Run:  python examples/train_publish_serve.py
"""

import json
import os
import tempfile
import urllib.request

from kubeflow_tpu.core.object import ObjectMeta
from kubeflow_tpu.core.serving import (
    BatchingSpec, ExplainerSpec, InferenceService, InferenceServiceSpec,
    ModelSpec, PredictorSpec,
)
from kubeflow_tpu.sdk import Client


def main() -> None:
    base_dir = tempfile.mkdtemp(prefix="kftpu-seam-")
    print("platform dir (checkpoints, artifact store, logs):", base_dir)
    client = Client.local(base_dir=base_dir)
    try:
        # 1. dataset → artifact://corpus@1
        corpus = os.path.join(client.cp.config.base_dir, "corpus.txt")
        with open(corpus, "w") as f:
            f.write("The quick brown fox jumps over the lazy dog. " * 200)
        ds = client.publish_file(corpus, name="corpus")
        print("dataset:", ds)

        # 2. train on the published dataset (BPE trained from it too)
        client.train(
            "seam", model="tiny",
            model_overrides={"vocab_size": 512, "max_seq_len": 64},
            steps=30, dataset_uri=ds, train_tokenizer_vocab=300,
            data={"global_batch": 8}, checkpoint=True,
            wait=True, timeout=600)

        # 3. checkpoint dir → artifact://demo-model@1 (a tree artifact)
        ckpt = os.path.join(client.cp.config.base_dir, "default", "seam",
                            "ckpt")
        model_uri = client.publish_model(ckpt, name="demo-model", version="1")
        print("model:", model_uri)

        # 4. serve by name — no file paths cross the subsystems
        isvc = client.apply(InferenceService(
            metadata=ObjectMeta(name="demo"),
            spec=InferenceServiceSpec(
                predictor=PredictorSpec(
                    model=ModelSpec(
                        model_name="demo", storage_uri=model_uri,
                        config={"preset": "tiny",
                                "overrides": {"vocab_size": 512,
                                              "max_seq_len": 64}}),
                    batching=BatchingSpec(max_batch_size=4, max_seq_len=64,
                                          prefill_buckets=[32])),
                explainer=ExplainerSpec(handler="grad_x_input"))))
        ready = client.wait_for(isvc, "Ready", timeout=300)

        def post(path, body):
            req = urllib.request.Request(
                ready.status.url + path, data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=180) as r:
                return json.loads(r.read())

        out = post("/v1/completions", {"prompt": "The quick",
                                       "max_tokens": 8})
        print("completion:", repr(out["choices"][0]["text"]))
        exp = post("/v1/models/demo:explain", {"instances": ["The quick"]})
        scores = exp["explanations"][0]
        print("attribution:", list(zip(scores["tokens"],
                                       [round(s, 3)
                                        for s in scores["scores"]])))
    finally:
        client.shutdown()


if __name__ == "__main__":
    main()
