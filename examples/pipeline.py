"""Example pipeline (KFP analog): compile + run with
    python examples/pipeline.py
or upload via the SDK (Client.upload_pipeline / create_run)."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubeflow_tpu.pipelines import dsl  # noqa: E402


@dsl.component
def make_dataset(n: int) -> list:
    return [i * i for i in range(n)]


@dsl.component
def split(data: list) -> dict:
    cut = int(len(data) * 0.8)
    return {"train": data[:cut], "holdout": data[cut:]}


@dsl.component
def train_model(splits: dict, lr: float) -> float:
    return sum(splits["train"]) * lr    # stand-in for a JAXJob submission


@dsl.component
def evaluate(score: float) -> str:
    return "ship" if score > 0 else "hold"


@dsl.pipeline(name="example-train")
def example_train(n: int = 10, lr: float = 0.1):
    d = make_dataset(n=n)
    s = split(data=d.output)
    m = train_model(splits=s.output, lr=lr)
    with dsl.Condition(m.output > 0.0):
        evaluate(score=m.output)


if __name__ == "__main__":
    import tempfile

    from kubeflow_tpu.pipelines.artifacts import ArtifactStore
    from kubeflow_tpu.pipelines.compiler import compile_pipeline, to_yaml
    from kubeflow_tpu.pipelines.executor import PipelineExecutor
    from kubeflow_tpu.pipelines.metadata import MetadataStore

    ir = compile_pipeline(example_train)
    print(to_yaml(ir))
    tmp = tempfile.mkdtemp()
    ex = PipelineExecutor(ArtifactStore(tmp + "/cas"),
                          MetadataStore(tmp + "/md.db"))
    res = ex.run(ir, run_name="example")
    for name, st in res.tasks.items():
        print(f"{name}: {st.phase.value} outputs={st.outputs}")
