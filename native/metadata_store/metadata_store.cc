// TPU-platform metadata store — the ML-Metadata analog, in C++ on SQLite.
//
// The reference stack's one C++ service is ml-metadata (SURVEY.md §2.5#41;
// (U) google/ml-metadata ml_metadata/metadata_store/metadata_store_server_main
// .cc): a typed Artifact/Execution/Context store with a lineage (Event) graph
// backing KFP's driver/cache/lineage. This rebuild keeps the same concepts —
// types, artifacts, executions, contexts, events, associations/attributions,
// typed properties — behind a flat C ABI consumed via ctypes (pybind11 is not
// in the image). In-process by design: the platform is single-host, so a gRPC
// hop would be pure overhead.
//
// Concurrency: one sqlite connection per handle, serialized by a mutex.
// All multi-statement writes run in IMMEDIATE transactions.

#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#include "sqlite3_api.h"

namespace {

struct Store {
  sqlite3* db = nullptr;
  std::mutex mu;
};

const char* kSchema = R"sql(
PRAGMA journal_mode=WAL;
PRAGMA synchronous=NORMAL;
CREATE TABLE IF NOT EXISTS types(
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  kind INTEGER NOT NULL,            -- 0 artifact, 1 execution, 2 context
  name TEXT NOT NULL,
  UNIQUE(kind, name));
CREATE TABLE IF NOT EXISTS artifacts(
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  type_id INTEGER NOT NULL,
  uri TEXT NOT NULL DEFAULT '',
  state INTEGER NOT NULL DEFAULT 0, -- 0 unknown, 1 pending, 2 live, 3 deleted
  create_ts INTEGER NOT NULL DEFAULT (strftime('%s','now')));
CREATE TABLE IF NOT EXISTS executions(
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  type_id INTEGER NOT NULL,
  state INTEGER NOT NULL DEFAULT 0, -- 0 new, 1 running, 2 complete, 3 failed, 4 cached, 5 canceled
  create_ts INTEGER NOT NULL DEFAULT (strftime('%s','now')));
CREATE TABLE IF NOT EXISTS contexts(
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  type_id INTEGER NOT NULL,
  name TEXT NOT NULL,
  UNIQUE(type_id, name));
CREATE TABLE IF NOT EXISTS properties(
  kind INTEGER NOT NULL,            -- owner kind: 0/1/2 as above
  owner_id INTEGER NOT NULL,
  key TEXT NOT NULL,
  tag INTEGER NOT NULL,             -- 0 int, 1 double, 2 string
  ival INTEGER, dval REAL, sval TEXT,
  PRIMARY KEY(kind, owner_id, key));
CREATE INDEX IF NOT EXISTS properties_by_value
  ON properties(kind, key, sval);
CREATE TABLE IF NOT EXISTS events(
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  execution_id INTEGER NOT NULL,
  artifact_id INTEGER NOT NULL,
  type INTEGER NOT NULL,            -- 0 input, 1 output
  path TEXT NOT NULL DEFAULT '',
  ts INTEGER NOT NULL DEFAULT (strftime('%s','now')));
CREATE INDEX IF NOT EXISTS events_by_execution ON events(execution_id);
CREATE INDEX IF NOT EXISTS events_by_artifact ON events(artifact_id);
CREATE TABLE IF NOT EXISTS associations(
  context_id INTEGER NOT NULL, execution_id INTEGER NOT NULL,
  PRIMARY KEY(context_id, execution_id));
CREATE TABLE IF NOT EXISTS attributions(
  context_id INTEGER NOT NULL, artifact_id INTEGER NOT NULL,
  PRIMARY KEY(context_id, artifact_id));
CREATE TABLE IF NOT EXISTS observations(
  trial_id INTEGER NOT NULL,        -- execution id of the trial
  metric TEXT NOT NULL,
  step INTEGER NOT NULL,
  value REAL NOT NULL,
  ts INTEGER NOT NULL DEFAULT (strftime('%s','now')),
  PRIMARY KEY(trial_id, metric, step));
)sql";

// One prepared statement executed to completion; returns last error code.
class Stmt {
 public:
  Stmt(sqlite3* db, const char* sql) {
    rc_ = sqlite3_prepare_v2(db, sql, -1, &stmt_, nullptr);
  }
  ~Stmt() {
    if (stmt_) sqlite3_finalize(stmt_);
  }
  bool ok() const { return rc_ == SQLITE_OK && stmt_ != nullptr; }
  sqlite3_stmt* get() { return stmt_; }
  void bind_int(int i, sqlite3_int64 v) { sqlite3_bind_int64(stmt_, i, v); }
  void bind_double(int i, double v) { sqlite3_bind_double(stmt_, i, v); }
  void bind_text(int i, const char* v) {
    if (v) sqlite3_bind_text(stmt_, i, v, -1, SQLITE_TRANSIENT);
    else sqlite3_bind_null(stmt_, i);
  }
  int step() { return sqlite3_step(stmt_); }

 private:
  sqlite3_stmt* stmt_ = nullptr;
  int rc_;
};

bool exec(Store* s, const char* sql) {
  char* err = nullptr;
  if (sqlite3_exec(s->db, sql, nullptr, nullptr, &err) != SQLITE_OK) {
    if (err) sqlite3_free(err);
    return false;
  }
  return true;
}

int fill_ids(Stmt& q, int64_t* out, int cap) {
  int n = 0;
  while (q.step() == SQLITE_ROW) {
    if (n < cap) out[n] = sqlite3_column_int64(q.get(), 0);
    ++n;
  }
  return n;  // may exceed cap: caller sees truncation
}

}  // namespace

extern "C" {

void* ms_open(const char* path, char* err, int errcap) {
  auto* s = new Store();
  if (sqlite3_open(path, &s->db) != SQLITE_OK) {
    if (err && errcap > 0)
      snprintf(err, errcap, "%s", s->db ? sqlite3_errmsg(s->db) : "open failed");
    if (s->db) sqlite3_close(s->db);
    delete s;
    return nullptr;
  }
  if (!exec(s, kSchema)) {
    if (err && errcap > 0) snprintf(err, errcap, "%s", sqlite3_errmsg(s->db));
    sqlite3_close(s->db);
    delete s;
    return nullptr;
  }
  return s;
}

void ms_close(void* h) {
  auto* s = static_cast<Store*>(h);
  if (!s) return;
  sqlite3_close(s->db);
  delete s;
}

// -- types ---------------------------------------------------------------------

int64_t ms_put_type(void* h, int kind, const char* name) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  {
    Stmt ins(s->db, "INSERT OR IGNORE INTO types(kind,name) VALUES(?,?)");
    if (!ins.ok()) return -1;
    ins.bind_int(1, kind);
    ins.bind_text(2, name);
    if (ins.step() != SQLITE_DONE) return -1;
  }
  Stmt q(s->db, "SELECT id FROM types WHERE kind=? AND name=?");
  if (!q.ok()) return -1;
  q.bind_int(1, kind);
  q.bind_text(2, name);
  return q.step() == SQLITE_ROW ? sqlite3_column_int64(q.get(), 0) : -1;
}

int64_t ms_get_type(void* h, int kind, const char* name) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Stmt q(s->db, "SELECT id FROM types WHERE kind=? AND name=?");
  if (!q.ok()) return -1;
  q.bind_int(1, kind);
  q.bind_text(2, name);
  return q.step() == SQLITE_ROW ? sqlite3_column_int64(q.get(), 0) : -1;
}

// -- nodes ---------------------------------------------------------------------

int64_t ms_create_artifact(void* h, int64_t type_id, const char* uri, int state) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Stmt q(s->db, "INSERT INTO artifacts(type_id,uri,state) VALUES(?,?,?)");
  if (!q.ok()) return -1;
  q.bind_int(1, type_id);
  q.bind_text(2, uri ? uri : "");
  q.bind_int(3, state);
  if (q.step() != SQLITE_DONE) return -1;
  return sqlite3_last_insert_rowid(s->db);
}

int ms_update_artifact(void* h, int64_t id, const char* uri, int state) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Stmt q(s->db, uri ? "UPDATE artifacts SET uri=?, state=? WHERE id=?"
                    : "UPDATE artifacts SET state=? WHERE id=?");
  if (!q.ok()) return -1;
  int i = 1;
  if (uri) q.bind_text(i++, uri);
  q.bind_int(i++, state);
  q.bind_int(i, id);
  return q.step() == SQLITE_DONE ? 0 : -1;
}

int ms_get_artifact(void* h, int64_t id, char* uri, int uricap,
                    int* state, int64_t* type_id) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Stmt q(s->db, "SELECT uri,state,type_id FROM artifacts WHERE id=?");
  if (!q.ok()) return -1;
  q.bind_int(1, id);
  if (q.step() != SQLITE_ROW) return -1;
  if (uri && uricap > 0)
    snprintf(uri, uricap, "%s", sqlite3_column_text(q.get(), 0));
  if (state) *state = (int)sqlite3_column_int64(q.get(), 1);
  if (type_id) *type_id = sqlite3_column_int64(q.get(), 2);
  return 0;
}

int64_t ms_create_execution(void* h, int64_t type_id, int state) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Stmt q(s->db, "INSERT INTO executions(type_id,state) VALUES(?,?)");
  if (!q.ok()) return -1;
  q.bind_int(1, type_id);
  q.bind_int(2, state);
  if (q.step() != SQLITE_DONE) return -1;
  return sqlite3_last_insert_rowid(s->db);
}

int ms_update_execution_state(void* h, int64_t id, int state) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Stmt q(s->db, "UPDATE executions SET state=? WHERE id=?");
  if (!q.ok()) return -1;
  q.bind_int(1, state);
  q.bind_int(2, id);
  return q.step() == SQLITE_DONE ? 0 : -1;
}

int ms_get_execution(void* h, int64_t id, int* state, int64_t* type_id) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Stmt q(s->db, "SELECT state,type_id FROM executions WHERE id=?");
  if (!q.ok()) return -1;
  q.bind_int(1, id);
  if (q.step() != SQLITE_ROW) return -1;
  if (state) *state = (int)sqlite3_column_int64(q.get(), 0);
  if (type_id) *type_id = sqlite3_column_int64(q.get(), 1);
  return 0;
}

int64_t ms_create_context(void* h, int64_t type_id, const char* name) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  {
    Stmt ins(s->db,
             "INSERT OR IGNORE INTO contexts(type_id,name) VALUES(?,?)");
    if (!ins.ok()) return -1;
    ins.bind_int(1, type_id);
    ins.bind_text(2, name);
    if (ins.step() != SQLITE_DONE) return -1;
  }
  Stmt q(s->db, "SELECT id FROM contexts WHERE type_id=? AND name=?");
  if (!q.ok()) return -1;
  q.bind_int(1, type_id);
  q.bind_text(2, name);
  return q.step() == SQLITE_ROW ? sqlite3_column_int64(q.get(), 0) : -1;
}

int ms_list_by_type(void* h, int kind, int64_t type_id, int64_t* out, int cap) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  const char* sql =
      kind == 0 ? "SELECT id FROM artifacts WHERE type_id=? ORDER BY id"
      : kind == 1 ? "SELECT id FROM executions WHERE type_id=? ORDER BY id"
                  : "SELECT id FROM contexts WHERE type_id=? ORDER BY id";
  Stmt q(s->db, sql);
  if (!q.ok()) return -1;
  q.bind_int(1, type_id);
  return fill_ids(q, out, cap);
}

// -- properties ----------------------------------------------------------------

int ms_put_property(void* h, int kind, int64_t owner, const char* key,
                    int tag, int64_t ival, double dval, const char* sval) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Stmt q(s->db,
         "INSERT OR REPLACE INTO properties(kind,owner_id,key,tag,ival,dval,sval)"
         " VALUES(?,?,?,?,?,?,?)");
  if (!q.ok()) return -1;
  q.bind_int(1, kind);
  q.bind_int(2, owner);
  q.bind_text(3, key);
  q.bind_int(4, tag);
  q.bind_int(5, ival);
  q.bind_double(6, dval);
  q.bind_text(7, sval);
  return q.step() == SQLITE_DONE ? 0 : -1;
}

int ms_get_property(void* h, int kind, int64_t owner, const char* key,
                    int* tag, int64_t* ival, double* dval,
                    char* sbuf, int scap) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Stmt q(s->db,
         "SELECT tag,ival,dval,sval FROM properties"
         " WHERE kind=? AND owner_id=? AND key=?");
  if (!q.ok()) return -1;
  q.bind_int(1, kind);
  q.bind_int(2, owner);
  q.bind_text(3, key);
  if (q.step() != SQLITE_ROW) return -1;
  if (tag) *tag = (int)sqlite3_column_int64(q.get(), 0);
  if (ival) *ival = sqlite3_column_int64(q.get(), 1);
  if (dval) *dval = sqlite3_column_double(q.get(), 2);
  if (sbuf && scap > 0) {
    const unsigned char* t = sqlite3_column_text(q.get(), 3);
    snprintf(sbuf, scap, "%s", t ? (const char*)t : "");
  }
  return 0;
}

int ms_list_property_keys(void* h, int kind, int64_t owner,
                          char* buf, int cap) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Stmt q(s->db,
         "SELECT key FROM properties WHERE kind=? AND owner_id=? ORDER BY key");
  if (!q.ok()) return -1;
  q.bind_int(1, kind);
  q.bind_int(2, owner);
  std::string joined;
  while (q.step() == SQLITE_ROW) {
    if (!joined.empty()) joined += '\n';
    joined += (const char*)sqlite3_column_text(q.get(), 0);
  }
  if (buf && cap > 0) snprintf(buf, cap, "%s", joined.c_str());
  return (int)joined.size();
}

int ms_find_executions_by_property(void* h, const char* key, const char* sval,
                                   int64_t* out, int cap) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Stmt q(s->db,
         "SELECT owner_id FROM properties"
         " WHERE kind=1 AND key=? AND sval=? ORDER BY owner_id");
  if (!q.ok()) return -1;
  q.bind_text(1, key);
  q.bind_text(2, sval);
  return fill_ids(q, out, cap);
}

// -- lineage -------------------------------------------------------------------

int ms_put_event(void* h, int64_t exec, int64_t art, int type,
                 const char* path) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Stmt q(s->db,
         "INSERT INTO events(execution_id,artifact_id,type,path) VALUES(?,?,?,?)");
  if (!q.ok()) return -1;
  q.bind_int(1, exec);
  q.bind_int(2, art);
  q.bind_int(3, type);
  q.bind_text(4, path ? path : "");
  return q.step() == SQLITE_DONE ? 0 : -1;
}

// Parallel arrays: artifact ids + event types; paths newline-joined in pathbuf.
int ms_events_by_execution(void* h, int64_t exec, int64_t* art_ids,
                           int* types, char* pathbuf, int pathcap, int cap) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Stmt q(s->db,
         "SELECT artifact_id,type,path FROM events"
         " WHERE execution_id=? ORDER BY id");
  if (!q.ok()) return -1;
  q.bind_int(1, exec);
  int n = 0;
  std::string paths;
  while (q.step() == SQLITE_ROW) {
    if (n < cap) {
      art_ids[n] = sqlite3_column_int64(q.get(), 0);
      types[n] = (int)sqlite3_column_int64(q.get(), 1);
      if (n) paths += '\n';
      paths += (const char*)sqlite3_column_text(q.get(), 2);
    }
    ++n;
  }
  if (pathbuf && pathcap > 0) snprintf(pathbuf, pathcap, "%s", paths.c_str());
  return n;
}

int ms_events_by_artifact(void* h, int64_t art, int64_t* exec_ids,
                          int* types, int cap) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Stmt q(s->db,
         "SELECT execution_id,type FROM events"
         " WHERE artifact_id=? ORDER BY id");
  if (!q.ok()) return -1;
  q.bind_int(1, art);
  int n = 0;
  while (q.step() == SQLITE_ROW) {
    if (n < cap) {
      exec_ids[n] = sqlite3_column_int64(q.get(), 0);
      types[n] = (int)sqlite3_column_int64(q.get(), 1);
    }
    ++n;
  }
  return n;
}

// -- contexts ------------------------------------------------------------------

int ms_add_association(void* h, int64_t ctx, int64_t exec) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Stmt q(s->db,
         "INSERT OR IGNORE INTO associations(context_id,execution_id)"
         " VALUES(?,?)");
  if (!q.ok()) return -1;
  q.bind_int(1, ctx);
  q.bind_int(2, exec);
  return q.step() == SQLITE_DONE ? 0 : -1;
}

int ms_add_attribution(void* h, int64_t ctx, int64_t art) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Stmt q(s->db,
         "INSERT OR IGNORE INTO attributions(context_id,artifact_id)"
         " VALUES(?,?)");
  if (!q.ok()) return -1;
  q.bind_int(1, ctx);
  q.bind_int(2, art);
  return q.step() == SQLITE_DONE ? 0 : -1;
}

int ms_list_context_executions(void* h, int64_t ctx, int64_t* out, int cap) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Stmt q(s->db,
         "SELECT execution_id FROM associations WHERE context_id=?"
         " ORDER BY execution_id");
  if (!q.ok()) return -1;
  q.bind_int(1, ctx);
  return fill_ids(q, out, cap);
}

int ms_list_context_artifacts(void* h, int64_t ctx, int64_t* out, int cap) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Stmt q(s->db,
         "SELECT artifact_id FROM attributions WHERE context_id=?"
         " ORDER BY artifact_id");
  if (!q.ok()) return -1;
  q.bind_int(1, ctx);
  return fill_ids(q, out, cap);
}

// -- observations (katib observation_logs analog — SURVEY.md §2.4#33) ----------
//
// A dedicated (trial, metric, step) → value table: one row per point, one
// upsert per point inside one IMMEDIATE transaction. The previous encoding —
// one PROPERTY row per point with the step packed into the key — paid a
// string key per lookup and rode the generic properties index; a 1e5-step
// log on one execution node was a crawl, and the gRPC DBManager surface now
// invites external writers at exactly that scale.

int ms_report_observations(void* h, int64_t trial, const char* metric,
                           const int64_t* steps, const double* values, int n) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  if (n <= 0) return 0;
  if (!exec(s, "BEGIN IMMEDIATE")) return -1;
  Stmt q(s->db,
         "INSERT INTO observations(trial_id,metric,step,value)"
         " VALUES(?,?,?,?) ON CONFLICT(trial_id,metric,step)"
         " DO UPDATE SET value=excluded.value, ts=strftime('%s','now')");
  if (!q.ok()) {
    exec(s, "ROLLBACK");
    return -1;
  }
  for (int i = 0; i < n; ++i) {
    sqlite3_reset(q.get());
    q.bind_int(1, trial);
    q.bind_text(2, metric);
    q.bind_int(3, steps[i]);
    q.bind_double(4, values[i]);
    if (q.step() != SQLITE_DONE) {
      exec(s, "ROLLBACK");
      return -1;
    }
  }
  if (!exec(s, "COMMIT")) {
    // A failed COMMIT (e.g. SQLITE_BUSY from a cross-process reader) keeps
    // the transaction open; without the rollback every later write on this
    // handle would wedge or silently land in the stale transaction.
    exec(s, "ROLLBACK");
    return -1;
  }
  return 0;
}

// Series ordered by step; fills up to cap, returns TOTAL row count (callers
// grow the buffers and retry on truncation, the fill_ids convention).
int ms_get_observations(void* h, int64_t trial, const char* metric,
                        int64_t* steps, double* values, int cap) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Stmt q(s->db,
         "SELECT step,value FROM observations"
         " WHERE trial_id=? AND metric=? ORDER BY step");
  if (!q.ok()) return -1;
  q.bind_int(1, trial);
  q.bind_text(2, metric);
  int n = 0;
  while (q.step() == SQLITE_ROW) {
    if (n < cap) {
      steps[n] = sqlite3_column_int64(q.get(), 0);
      values[n] = sqlite3_column_double(q.get(), 1);
    }
    ++n;
  }
  return n;
}

// Distinct metric names of a trial, newline-joined (the
// ms_list_property_keys convention); returns the joined byte length.
int ms_observation_metrics(void* h, int64_t trial, char* buf, int cap) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Stmt q(s->db,
         "SELECT DISTINCT metric FROM observations WHERE trial_id=?"
         " ORDER BY metric");
  if (!q.ok()) return -1;
  q.bind_int(1, trial);
  std::string joined;
  while (q.step() == SQLITE_ROW) {
    if (!joined.empty()) joined += '\n';
    joined += (const char*)sqlite3_column_text(q.get(), 0);
  }
  if (buf && cap > 0) snprintf(buf, cap, "%s", joined.c_str());
  return (int)joined.size();
}

}  // extern "C"
