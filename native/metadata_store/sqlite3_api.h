// Minimal declarations for the stable public SQLite3 C ABI (the subset this
// store uses). The runtime image ships libsqlite3.so.0 but not the dev
// header; these prototypes follow the documented public API
// (sqlite.org/c3ref) and link against the system library.
#pragma once

#include <cstdint>

extern "C" {

typedef struct sqlite3 sqlite3;
typedef struct sqlite3_stmt sqlite3_stmt;
typedef int64_t sqlite3_int64;

int sqlite3_open(const char* filename, sqlite3** db);
int sqlite3_close(sqlite3*);
int sqlite3_exec(sqlite3*, const char* sql,
                 int (*callback)(void*, int, char**, char**), void*,
                 char** errmsg);
void sqlite3_free(void*);
const char* sqlite3_errmsg(sqlite3*);

int sqlite3_prepare_v2(sqlite3*, const char* sql, int nbyte,
                       sqlite3_stmt** stmt, const char** tail);
int sqlite3_step(sqlite3_stmt*);
int sqlite3_reset(sqlite3_stmt*);
int sqlite3_finalize(sqlite3_stmt*);

int sqlite3_bind_int64(sqlite3_stmt*, int, sqlite3_int64);
int sqlite3_bind_double(sqlite3_stmt*, int, double);
int sqlite3_bind_text(sqlite3_stmt*, int, const char*, int, void (*)(void*));
int sqlite3_bind_null(sqlite3_stmt*, int);

int sqlite3_column_type(sqlite3_stmt*, int);
sqlite3_int64 sqlite3_column_int64(sqlite3_stmt*, int);
double sqlite3_column_double(sqlite3_stmt*, int);
const unsigned char* sqlite3_column_text(sqlite3_stmt*, int);

sqlite3_int64 sqlite3_last_insert_rowid(sqlite3*);

}  // extern "C"

// Return codes / constants used here (public ABI values).
#define SQLITE_OK 0
#define SQLITE_ROW 100
#define SQLITE_DONE 101
#define SQLITE_NULL 5
#define SQLITE_TRANSIENT ((void (*)(void*))(-1))
