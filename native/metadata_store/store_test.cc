// Native smoke/sanitizer test for the metadata store (SURVEY.md §4
// 'rebuild translation': TSan/ASan builds for the C++ metadata store —
// the race/sanitizer coverage the reference gets from `go test -race`).
//
// Build & run via the Makefile: `make test-asan` / `make test-tsan`.
// Exercises the full C ABI incl. concurrent writers; exits nonzero on any
// mismatch, and the sanitizers abort on memory/thread errors.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void* ms_open(const char* path, char* err, int errcap);
void ms_close(void* h);
int64_t ms_put_type(void* h, int kind, const char* name);
int64_t ms_get_type(void* h, int kind, const char* name);
int64_t ms_create_artifact(void* h, int64_t type_id, const char* uri, int state);
int ms_update_artifact(void* h, int64_t id, const char* uri, int state);
int ms_get_artifact(void* h, int64_t id, char* uri, int uricap, int* state,
                    int64_t* type_id);
int64_t ms_create_execution(void* h, int64_t type_id, int state);
int ms_update_execution_state(void* h, int64_t id, int state);
int ms_get_execution(void* h, int64_t id, int* state, int64_t* type_id);
int64_t ms_create_context(void* h, int64_t type_id, const char* name);
int ms_list_by_type(void* h, int kind, int64_t type_id, int64_t* out, int cap);
int ms_put_property(void* h, int kind, int64_t owner, const char* key, int tag,
                    int64_t ival, double dval, const char* sval);
int ms_get_property(void* h, int kind, int64_t owner, const char* key,
                    int* tag, int64_t* ival, double* dval, char* sbuf,
                    int scap);
int ms_find_executions_by_property(void* h, const char* key, const char* sval,
                                   int64_t* out, int cap);
int ms_put_event(void* h, int64_t exec, int64_t art, int type,
                 const char* path);
int ms_events_by_execution(void* h, int64_t exec, int64_t* art_ids, int* types,
                           char* pathbuf, int pathcap, int cap);
int ms_events_by_artifact(void* h, int64_t art, int64_t* exec_ids, int* types,
                          int cap);
int ms_add_association(void* h, int64_t ctx, int64_t exec);
int ms_add_attribution(void* h, int64_t ctx, int64_t art);
int ms_list_context_executions(void* h, int64_t ctx, int64_t* out, int cap);
int ms_report_observations(void* h, int64_t trial, const char* metric,
                           const int64_t* steps, const double* values, int n);
int ms_get_observations(void* h, int64_t trial, const char* metric,
                        int64_t* steps, double* values, int cap);
int ms_observation_metrics(void* h, int64_t trial, char* buf, int cap);
}

#define CHECK(cond)                                                   \
  do {                                                                \
    if (!(cond)) {                                                    \
      std::fprintf(stderr, "CHECK failed at %s:%d: %s\n", __FILE__,   \
                   __LINE__, #cond);                                  \
      return 1;                                                       \
    }                                                                 \
  } while (0)

int main(int argc, char** argv) {
  std::string path = argc > 1 ? argv[1] : "/tmp/ms_native_test.db";
  std::remove(path.c_str());
  char err[256] = {0};
  void* h = ms_open(path.c_str(), err, sizeof(err));
  if (!h) {
    std::fprintf(stderr, "open failed: %s\n", err);
    return 1;
  }

  // Types dedupe per kind.
  int64_t t_ds = ms_put_type(h, 0, "Dataset");
  CHECK(t_ds > 0);
  CHECK(ms_put_type(h, 0, "Dataset") == t_ds);
  CHECK(ms_get_type(h, 0, "Dataset") == t_ds);
  int64_t t_exec = ms_put_type(h, 1, "train");
  CHECK(t_exec != t_ds || t_exec > 0);

  // Artifact round trip + properties of every tag.
  int64_t a = ms_create_artifact(h, t_ds, "cas://abc", 1);
  CHECK(a > 0);
  CHECK(ms_put_property(h, 0, a, "rows", 0, 42, 0, "") == 0);
  CHECK(ms_put_property(h, 0, a, "split", 1, 0, 0.25, "") == 0);
  CHECK(ms_put_property(h, 0, a, "name", 2, 0, 0, "train-set") == 0);
  char uri[256];
  int state = -1;
  int64_t tid = -1;
  CHECK(ms_get_artifact(h, a, uri, sizeof(uri), &state, &tid) == 0);
  CHECK(std::strcmp(uri, "cas://abc") == 0 && state == 1 && tid == t_ds);
  int tag;
  int64_t iv;
  double dv;
  char sv[128];
  CHECK(ms_get_property(h, 0, a, "rows", &tag, &iv, &dv, sv, sizeof(sv)) == 0);
  CHECK(tag == 0 && iv == 42);
  CHECK(ms_get_property(h, 0, a, "nope", &tag, &iv, &dv, sv, sizeof(sv)) != 0);
  CHECK(ms_update_artifact(h, a, "cas://def", 2) == 0);
  CHECK(ms_get_artifact(h, a, uri, sizeof(uri), &state, nullptr) == 0);
  CHECK(std::strcmp(uri, "cas://def") == 0 && state == 2);

  // Execution lifecycle + cache-key lookup.
  int64_t e = ms_create_execution(h, t_exec, 1);
  CHECK(e > 0);
  CHECK(ms_put_property(h, 1, e, "cache_key", 2, 0, 0, "k123") == 0);
  CHECK(ms_update_execution_state(h, e, 2) == 0);
  int es;
  CHECK(ms_get_execution(h, e, &es, nullptr) == 0 && es == 2);
  int64_t hits[4];
  CHECK(ms_find_executions_by_property(h, "cache_key", "k123", hits, 4) == 1);
  CHECK(hits[0] == e);

  // Lineage events + context membership.
  int64_t model = ms_create_artifact(h, t_ds, "cas://model", 2);
  CHECK(ms_put_event(h, e, a, 0, "data") == 0);
  CHECK(ms_put_event(h, e, model, 1, "model") == 0);
  int64_t arts[8];
  int types[8];
  char paths[512];
  int n = ms_events_by_execution(h, e, arts, types, paths, sizeof(paths), 8);
  CHECK(n == 2 && arts[0] == a && types[0] == 0 && arts[1] == model &&
        types[1] == 1);
  CHECK(std::strcmp(paths, "data\nmodel") == 0);
  int64_t execs[8];
  CHECK(ms_events_by_artifact(h, model, execs, types, 8) == 1);
  CHECK(execs[0] == e && types[0] == 1);
  int64_t t_ctx = ms_put_type(h, 2, "run");
  int64_t ctx = ms_create_context(h, t_ctx, "r1");
  CHECK(ctx > 0);
  CHECK(ms_create_context(h, t_ctx, "r1") == ctx);  // get-or-create
  CHECK(ms_add_association(h, ctx, e) == 0);
  CHECK(ms_add_association(h, ctx, e) == 0);        // idempotent
  CHECK(ms_add_attribution(h, ctx, model) == 0);
  int64_t members[4];
  CHECK(ms_list_context_executions(h, ctx, members, 4) == 1);

  // Truncation contract: more rows than cap reports the true count.
  for (int i = 0; i < 20; i++) ms_create_artifact(h, t_ds, "cas://bulk", 0);
  int64_t small[4];
  CHECK(ms_list_by_type(h, 0, t_ds, small, 4) > 4);

  // Observations table: batch upsert, ordered read, truncation contract,
  // metric listing.
  {
    int64_t steps[6] = {30, 10, 20, 40, 50, 20};   // unordered + dup step
    double vals[6] = {3.0, 1.0, 2.0, 4.0, 5.0, 2.5};
    CHECK(ms_report_observations(h, e, "loss", steps, vals, 6) == 0);
    int64_t rs[8];
    double rv[8];
    int nobs = ms_get_observations(h, e, "loss", rs, rv, 8);
    CHECK(nobs == 5);                               // dup step upserted
    CHECK(rs[0] == 10 && rs[4] == 50);              // ordered by step
    CHECK(rv[1] == 2.5);                            // last write won step 20
    CHECK(ms_get_observations(h, e, "loss", rs, rv, 2) == 5);  // true count
    CHECK(ms_get_observations(h, e, "nope", rs, rv, 8) == 0);
    int64_t s2[1] = {1};
    double v2[1] = {0.9};
    CHECK(ms_report_observations(h, e, "accuracy", s2, v2, 1) == 0);
    char mbuf[128];
    CHECK(ms_observation_metrics(h, e, mbuf, sizeof(mbuf)) > 0);
    CHECK(std::strcmp(mbuf, "accuracy\nloss") == 0);
  }

  // Concurrent observation writers (TSan: the new table shares the handle
  // mutex; the IMMEDIATE transaction must not interleave).
  {
    std::vector<std::thread> obs_workers;
    for (int w = 0; w < 4; w++) {
      obs_workers.emplace_back([h, e, w] {
        char metric[32];
        std::snprintf(metric, sizeof(metric), "m%d", w);
        for (int i = 0; i < 25; i++) {
          int64_t s[4] = {i * 4, i * 4 + 1, i * 4 + 2, i * 4 + 3};
          double v[4] = {1.0 * i, 2.0 * i, 3.0 * i, 4.0 * i};
          ms_report_observations(h, e, metric, s, v, 4);
          int64_t rs[128];
          double rv[128];
          ms_get_observations(h, e, metric, rs, rv, 128);
        }
      });
    }
    for (auto& t : obs_workers) t.join();
    int64_t rs[128];
    double rv[128];
    for (int w = 0; w < 4; w++) {
      char metric[32];
      std::snprintf(metric, sizeof(metric), "m%d", w);
      CHECK(ms_get_observations(h, e, metric, rs, rv, 128) == 100);
    }
  }

  // Concurrent writers (the TSan target of this test).
  std::vector<std::thread> workers;
  for (int w = 0; w < 4; w++) {
    workers.emplace_back([h, t_ds, w] {
      for (int i = 0; i < 50; i++) {
        char u[64];
        std::snprintf(u, sizeof(u), "cas://w%d/%d", w, i);
        int64_t id = ms_create_artifact(h, t_ds, u, 1);
        ms_put_property(h, 0, id, "i", 0, i, 0, "");
        char buf[64];
        int st;
        ms_get_artifact(h, id, buf, sizeof(buf), &st, nullptr);
      }
    });
  }
  for (auto& t : workers) t.join();
  int64_t big[512];
  int total = ms_list_by_type(h, 0, t_ds, big, 512);
  CHECK(total == 1 + 1 + 20 + 200);  // a + model + bulk + concurrent

  ms_close(h);
  std::remove(path.c_str());
  std::printf("metadata store native test OK (%d artifacts)\n", total);
  return 0;
}
