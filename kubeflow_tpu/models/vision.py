"""Vision models: ViT encoder + CLIP dual encoder — BASELINE config 4
("ViT-L / CLIP via pipelines").

TPU-first choices:
- patchify is a reshape + one big matmul (not a conv): patches land on the
  MXU as a single [B·N, P²·C]×[P²·C, D] contraction.
- layers are stacked and traversed with `lax.scan` (depth-independent
  compile), rematerialized like the decoder.
- logical-axis sharding reuses parallel/sharding.py rules: batch over the
  data axes, heads/mlp over ``model``, params' embed dim over ``fsdp``.
- CLIP's contrastive loss contracts globally sharded feature matrices;
  GSPMD inserts the all-gather over the data axes (the in-batch negatives
  collective) — no hand-written collective needed.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

from kubeflow_tpu.models.layers import _init
from kubeflow_tpu.ops.attention import multi_head_attention
from kubeflow_tpu.parallel.sharding import (
    DEFAULT_RULES, LogicalRules, _is_spec_leaf, with_logical_constraint,
)

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    """Hashable (jit-static) ViT architecture description."""

    image_size: int = 224
    patch_size: int = 16
    channels: int = 3
    hidden: int = 1024
    n_layers: int = 24
    n_heads: int = 16
    mlp_dim: int = 4096
    num_classes: int = 1000       # classification head; 0 = feature output
    pool: str = "cls"             # cls | gap
    norm_eps: float = 1e-6
    scan_layers: bool = True
    remat_policy: str = "nothing_saveable"
    dtype: str = "bfloat16"
    param_dtype: str = "float32"

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def seq_len(self) -> int:
        return self.num_patches + (1 if self.pool == "cls" else 0)

    @property
    def head_dim(self) -> int:
        return self.hidden // self.n_heads

    @property
    def activation_dtype(self):
        return jnp.dtype(self.dtype)

    @property
    def weight_dtype(self):
        return jnp.dtype(self.param_dtype)


VIT_PRESETS: dict[str, ViTConfig] = {
    # Public ViT-L/16 architecture (AN IMAGE IS WORTH 16x16 WORDS table 1).
    "vit-l16": ViTConfig(hidden=1024, n_layers=24, n_heads=16, mlp_dim=4096),
    "vit-b16": ViTConfig(hidden=768, n_layers=12, n_heads=12, mlp_dim=3072),
    "tiny-vit": ViTConfig(image_size=32, patch_size=8, hidden=64, n_layers=2,
                          n_heads=4, mlp_dim=128, num_classes=10),
}


def vit_preset(name: str, **overrides) -> ViTConfig:
    return dataclasses.replace(VIT_PRESETS[name], **overrides)


# -- layers ----------------------------------------------------------------------


def _init_layernorm(cfg, dim: int):
    return ({"scale": jnp.ones((dim,), cfg.weight_dtype),
             "bias": jnp.zeros((dim,), cfg.weight_dtype)},
            {"scale": ("norm",), "bias": ("norm",)})


def _layernorm(p, x, eps: float):
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(-1, keepdims=True)
    out = (xf - mu) * jax.lax.rsqrt(var + eps)
    return (out * p["scale"] + p["bias"]).astype(x.dtype)


def _init_encoder_block(key, cfg: ViTConfig):
    k1, k2, k3, k4 = jax.random.split(key, 4)
    d, h, hd = cfg.hidden, cfg.n_heads, cfg.head_dim
    ln1, ln1_s = _init_layernorm(cfg, d)
    ln2, ln2_s = _init_layernorm(cfg, d)
    params = {
        "ln1": ln1, "ln2": ln2,
        "wqkv": _init(k1, (d, 3, h, hd), cfg.weight_dtype),
        "wo": _init(k2, (h, hd, d), cfg.weight_dtype),
        "w1": _init(k3, (d, cfg.mlp_dim), cfg.weight_dtype),
        "b1": jnp.zeros((cfg.mlp_dim,), cfg.weight_dtype),
        "w2": _init(k4, (cfg.mlp_dim, d), cfg.weight_dtype),
        "b2": jnp.zeros((d,), cfg.weight_dtype),
    }
    specs = {
        "ln1": ln1_s, "ln2": ln2_s,
        "wqkv": ("embed", None, "heads", "head_dim"),
        "wo": ("heads", "head_dim", "embed"),
        "w1": ("embed", "mlp"),
        "b1": ("mlp",),
        "w2": ("mlp", "embed"),
        "b2": ("norm",),
    }
    return params, specs


def _encoder_block(p, x, cfg: ViTConfig, *, causal: bool = False,
                   mesh=None, rules=DEFAULT_RULES):
    dt = cfg.activation_dtype
    h = _layernorm(p["ln1"], x, cfg.norm_eps)
    qkv = jnp.einsum("bsd,dthk->tbshk", h, p["wqkv"].astype(dt))
    out = multi_head_attention(qkv[0], qkv[1], qkv[2], causal=causal)
    out = jnp.einsum("bshk,hkd->bsd", out, p["wo"].astype(dt))
    x = x + out
    h = _layernorm(p["ln2"], x, cfg.norm_eps)
    h = jax.nn.gelu(h @ p["w1"].astype(dt) + p["b1"].astype(dt))
    x = x + (h @ p["w2"].astype(dt) + p["b2"].astype(dt))
    if mesh is not None:
        x = with_logical_constraint(x, ("batch", "act_seq", "act_embed"),
                                    mesh, rules)
    return x


def _remat(fn, policy: str):
    if policy == "none":
        return fn
    if policy == "nothing_saveable":
        return jax.checkpoint(fn, policy=jax.checkpoint_policies.nothing_saveable)
    return jax.checkpoint(fn)


def _encode(params, x, cfg: ViTConfig, *, causal=False, mesh=None,
            rules=DEFAULT_RULES):
    """Shared transformer-encoder trunk (scan over stacked blocks)."""
    if cfg.scan_layers:
        def body(carry, bp):
            return _encoder_block(bp, carry, cfg, causal=causal, mesh=mesh,
                                  rules=rules), None

        x, _ = jax.lax.scan(_remat(body, cfg.remat_policy), x,
                            params["layers"])
    else:
        for bp in params["layers"]:
            x = _encoder_block(bp, x, cfg, causal=causal, mesh=mesh,
                               rules=rules)
    return _layernorm(params["final_ln"], x, cfg.norm_eps)


# -- ViT -------------------------------------------------------------------------


def init_vit_params(key: jax.Array, cfg: ViTConfig) -> Params:
    k_patch, k_pos, k_layers, k_head = jax.random.split(key, 4)
    patch_dim = cfg.patch_size * cfg.patch_size * cfg.channels
    layer_keys = jax.random.split(k_layers, cfg.n_layers)
    if cfg.scan_layers:
        layers = jax.vmap(lambda k: _init_encoder_block(k, cfg)[0])(layer_keys)
    else:
        layers = [_init_encoder_block(k, cfg)[0] for k in layer_keys]
    final_ln, _ = _init_layernorm(cfg, cfg.hidden)
    params: Params = {
        "patch_embed": _init(k_patch, (patch_dim, cfg.hidden),
                             cfg.weight_dtype),
        "pos_embed": _init(k_pos, (cfg.seq_len, cfg.hidden),
                           cfg.weight_dtype, scale=0.02),
        "layers": layers,
        "final_ln": final_ln,
    }
    if cfg.pool == "cls":
        params["cls_token"] = jnp.zeros((cfg.hidden,), cfg.weight_dtype)
    if cfg.num_classes:
        params["head"] = _init(k_head, (cfg.hidden, cfg.num_classes),
                               cfg.weight_dtype)
    return params


def vit_param_specs(cfg: ViTConfig) -> Params:
    captured = {}

    def _shape_only():
        params, specs = _init_encoder_block(jax.random.PRNGKey(0), cfg)
        captured["specs"] = specs
        return params

    jax.eval_shape(_shape_only)
    block_specs = captured["specs"]
    if cfg.scan_layers:
        layer_specs = jax.tree.map(lambda s: ("layers",) + s, block_specs,
                                   is_leaf=_is_spec_leaf)
    else:
        layer_specs = [block_specs] * cfg.n_layers
    specs: Params = {
        "patch_embed": (None, "embed"),
        "pos_embed": (None, None),
        "layers": layer_specs,
        "final_ln": {"scale": ("norm",), "bias": ("norm",)},
    }
    if cfg.pool == "cls":
        specs["cls_token"] = ("norm",)
    if cfg.num_classes:
        specs["head"] = ("embed", "vocab")
    return specs


def patchify(images: jax.Array, patch: int) -> jax.Array:
    """[B, H, W, C] → [B, N, P²·C] without a conv (one reshape/transpose)."""
    b, h, w, c = images.shape
    gh, gw = h // patch, w // patch
    x = images.reshape(b, gh, patch, gw, patch, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, gh * gw, patch * patch * c)


def vit_forward(params: Params, images: jax.Array, cfg: ViTConfig, *,
                mesh=None, rules: LogicalRules = DEFAULT_RULES) -> jax.Array:
    """[B, H, W, C] images → [B, num_classes] logits (or [B, D] features)."""
    dt = cfg.activation_dtype
    x = patchify(images.astype(dt), cfg.patch_size)
    x = x @ params["patch_embed"].astype(dt)
    if cfg.pool == "cls":
        cls = jnp.broadcast_to(params["cls_token"].astype(dt),
                               (x.shape[0], 1, cfg.hidden))
        x = jnp.concatenate([cls, x], axis=1)
    x = x + params["pos_embed"].astype(dt)
    if mesh is not None:
        x = with_logical_constraint(x, ("batch", "act_seq", "act_embed"),
                                    mesh, rules)
    x = _encode(params, x, cfg, mesh=mesh, rules=rules)
    feats = x[:, 0] if cfg.pool == "cls" else x.mean(axis=1)
    if cfg.num_classes:
        return jnp.einsum("bd,dv->bv", feats, params["head"].astype(dt),
                          preferred_element_type=jnp.float32)
    return feats


def vit_loss(params: Params, batch: dict, cfg: ViTConfig, *,
             mesh=None, rules: LogicalRules = DEFAULT_RULES):
    """Cross-entropy classification. batch: {"images", "labels"}."""
    logits = vit_forward(params, batch["images"], cfg, mesh=mesh, rules=rules)
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    labels = batch["labels"]
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    loss = nll.mean()
    metrics = {
        "loss": loss,
        "accuracy": (logits.argmax(-1) == labels).mean(),
    }
    return loss, metrics


# -- CLIP ------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class CLIPConfig:
    """Dual encoder: ViT image tower + causal text tower + shared proj dim."""

    image: ViTConfig = dataclasses.field(
        default_factory=lambda: dataclasses.replace(
            VIT_PRESETS["vit-l16"], num_classes=0))
    text_vocab: int = 49408
    text_len: int = 77
    text_hidden: int = 768
    text_layers: int = 12
    text_heads: int = 12
    text_mlp: int = 3072
    proj_dim: int = 768
    dtype: str = "bfloat16"
    param_dtype: str = "float32"

    @property
    def text_cfg(self) -> ViTConfig:
        """The text tower reuses the encoder trunk config-shape."""
        return ViTConfig(
            hidden=self.text_hidden, n_layers=self.text_layers,
            n_heads=self.text_heads, mlp_dim=self.text_mlp,
            num_classes=0, scan_layers=True, dtype=self.dtype,
            param_dtype=self.param_dtype)


CLIP_PRESETS: dict[str, CLIPConfig] = {
    "clip-l14": CLIPConfig(),
    "tiny-clip": CLIPConfig(
        image=ViTConfig(image_size=32, patch_size=8, hidden=64, n_layers=2,
                        n_heads=4, mlp_dim=128, num_classes=0),
        text_vocab=256, text_len=16, text_hidden=64, text_layers=2,
        text_heads=4, text_mlp=128, proj_dim=32),
}


def clip_preset(name: str, **overrides) -> CLIPConfig:
    return dataclasses.replace(CLIP_PRESETS[name], **overrides)


def init_clip_params(key: jax.Array, cfg: CLIPConfig) -> Params:
    ki, kt, ke, kpos, kp1, kp2 = jax.random.split(key, 6)
    tcfg = cfg.text_cfg
    layer_keys = jax.random.split(kt, tcfg.n_layers)
    text_layers = jax.vmap(
        lambda k: _init_encoder_block(k, tcfg)[0])(layer_keys)
    final_ln, _ = _init_layernorm(tcfg, tcfg.hidden)
    return {
        "image": init_vit_params(ki, cfg.image),
        "text": {
            "embed": _init(ke, (cfg.text_vocab, tcfg.hidden),
                           tcfg.weight_dtype, scale=0.02),
            "pos_embed": _init(kpos, (cfg.text_len, tcfg.hidden),
                               tcfg.weight_dtype, scale=0.01),
            "layers": text_layers,
            "final_ln": final_ln,
        },
        "img_proj": _init(kp1, (cfg.image.hidden, cfg.proj_dim),
                          cfg.image.weight_dtype),
        "txt_proj": _init(kp2, (tcfg.hidden, cfg.proj_dim),
                          tcfg.weight_dtype),
        # CLIP's learned temperature, initialized to 1/0.07 as in the paper.
        "logit_scale": jnp.asarray(jnp.log(1.0 / 0.07), jnp.float32),
    }


def clip_param_specs(cfg: CLIPConfig) -> Params:
    tcfg = cfg.text_cfg
    text_block_specs = jax.tree.map(
        lambda s: ("layers",) + s,
        _encoder_block_specs(tcfg), is_leaf=_is_spec_leaf)
    return {
        "image": vit_param_specs(cfg.image),
        "text": {
            "embed": ("vocab", "embed_table"),
            "pos_embed": (None, None),
            "layers": text_block_specs,
            "final_ln": {"scale": ("norm",), "bias": ("norm",)},
        },
        "img_proj": ("embed", None),
        "txt_proj": ("embed", None),
        "logit_scale": (),
    }


def _encoder_block_specs(cfg: ViTConfig):
    captured = {}

    def _shape_only():
        params, specs = _init_encoder_block(jax.random.PRNGKey(0), cfg)
        captured["specs"] = specs
        return params

    jax.eval_shape(_shape_only)
    return captured["specs"]


def clip_encode_image(params: Params, images: jax.Array, cfg: CLIPConfig, *,
                      mesh=None, rules=DEFAULT_RULES) -> jax.Array:
    feats = vit_forward(params["image"], images, cfg.image, mesh=mesh,
                        rules=rules)
    return feats @ params["img_proj"].astype(feats.dtype)


def clip_encode_text(params: Params, tokens: jax.Array, cfg: CLIPConfig, *,
                     mesh=None, rules=DEFAULT_RULES) -> jax.Array:
    tcfg = cfg.text_cfg
    dt = tcfg.activation_dtype
    p = params["text"]
    x = p["embed"].astype(dt)[tokens] + p["pos_embed"].astype(dt)
    if mesh is not None:
        x = with_logical_constraint(x, ("batch", "act_seq", "act_embed"),
                                    mesh, rules)
    x = _encode(p, x, tcfg, causal=True, mesh=mesh, rules=rules)
    # EOT pooling: the highest token id marks end-of-text (CLIP convention).
    eot = tokens.argmax(axis=-1)
    feats = jnp.take_along_axis(x, eot[:, None, None].repeat(x.shape[-1], -1),
                                axis=1)[:, 0]
    return feats @ params["txt_proj"].astype(feats.dtype)


def clip_loss(params: Params, batch: dict, cfg: CLIPConfig, *,
              mesh=None, rules=DEFAULT_RULES):
    """Symmetric InfoNCE over the global batch. batch: {"images","tokens"}.

    Under pjit the feature matrices are batch-sharded; the [B, B] similarity
    einsum makes GSPMD all-gather the negatives over the data axes — the
    TPU-native equivalent of torch.distributed all_gather in open_clip."""
    img = clip_encode_image(params, batch["images"], cfg, mesh=mesh,
                            rules=rules).astype(jnp.float32)
    txt = clip_encode_text(params, batch["tokens"], cfg, mesh=mesh,
                           rules=rules).astype(jnp.float32)
    img = img / (jnp.linalg.norm(img, axis=-1, keepdims=True) + 1e-8)
    txt = txt / (jnp.linalg.norm(txt, axis=-1, keepdims=True) + 1e-8)
    scale = jnp.exp(jnp.clip(params["logit_scale"], -5.0, jnp.log(100.0)))
    logits = scale * img @ txt.T                      # [B, B]
    labels = jnp.arange(logits.shape[0])
    li = -jnp.take_along_axis(jax.nn.log_softmax(logits, axis=1),
                              labels[:, None], axis=1).mean()
    lt = -jnp.take_along_axis(jax.nn.log_softmax(logits, axis=0),
                              labels[None, :], axis=0).mean()
    loss = (li + lt) / 2
    metrics = {
        "loss": loss,
        "img_to_txt_acc": (logits.argmax(1) == labels).mean(),
        "temperature": 1.0 / scale,
    }
    return loss, metrics
