"""The decoder LLM: init, forward, loss — scan-over-layers, remat, logical
sharding specs.

Covers Llama-3 (RoPE+GQA+RMSNorm+SwiGLU), Gemma ((1+w) norms, embed scale,
GeGLU, tied embeddings, logit softcap) and Mixtral (MoE blocks) through
DecoderConfig flags. Layers are stacked on a leading axis and traversed with
`lax.scan` so compile time is depth-independent; the block is rematerialized
per the config policy (trades HBM for FLOPs — SURVEY.md task guidance).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from kubeflow_tpu.models.config import DecoderConfig
from kubeflow_tpu.models import layers as L
from kubeflow_tpu.parallel.sharding import (
    LogicalRules, DEFAULT_RULES, _is_spec_leaf, with_logical_constraint,
)

Params = dict[str, Any]


def _init_block(key, cfg: DecoderConfig):
    k_attn, k_mlp = jax.random.split(key)
    attn_p, attn_s = L.init_attention(k_attn, cfg)
    if cfg.is_moe:
        mlp_p, mlp_s = L.init_moe(k_mlp, cfg)
    else:
        mlp_p, mlp_s = L.init_mlp(k_mlp, cfg)
    ln1, ln1_s = L.init_rmsnorm(cfg)
    ln2, ln2_s = L.init_rmsnorm(cfg)
    params = {"attn": attn_p, "mlp": mlp_p, "ln1": ln1, "ln2": ln2}
    specs = {"attn": attn_s, "mlp": mlp_s, "ln1": ln1_s, "ln2": ln2_s}
    return params, specs


def init_decoder_params(key: jax.Array, cfg: DecoderConfig) -> Params:
    k_embed, k_layers, k_head = jax.random.split(key, 3)
    tok, _ = L.init_embedding(k_embed, cfg)

    if cfg.scan_layers:
        # Stack per-layer params on a leading axis via vmapped init.
        layer_keys = jax.random.split(k_layers, cfg.n_layers)
        stacked = jax.vmap(lambda k: _init_block(k, cfg)[0])(layer_keys)
        layers_params = stacked
    else:
        layers_params = [
            _init_block(k, cfg)[0] for k in jax.random.split(k_layers, cfg.n_layers)
        ]

    final_norm, _ = L.init_rmsnorm(cfg)
    params: Params = {"embed": tok, "layers": layers_params, "final_norm": final_norm}
    if not cfg.tie_embeddings:
        params["lm_head"] = L._init(k_head, (cfg.hidden, cfg.vocab_size),
                                    cfg.weight_dtype)
    return params


def _block_specs(cfg: DecoderConfig):
    """Logical-axis spec tree for one decoder block (no params materialize:
    llama3-70b's block is ~GBs — trace under eval_shape, capture the static
    spec tree on the side)."""
    captured = {}

    def _shape_only():
        params, specs = _init_block(jax.random.PRNGKey(0), cfg)
        captured["specs"] = specs
        return params

    jax.eval_shape(_shape_only)
    return captured["specs"]


def decoder_param_specs(cfg: DecoderConfig) -> Params:
    """Logical-axis spec tree mirroring init_decoder_params' structure.

    The stacked layer axis prepends the "layers" logical axis to every
    per-layer leaf when scanning."""
    block_specs = _block_specs(cfg)

    if cfg.scan_layers:
        def stack_spec(s):
            return ("layers",) + s
        layer_specs = jax.tree.map(stack_spec, block_specs,
                                   is_leaf=_is_spec_leaf)
    else:
        layer_specs = [block_specs] * cfg.n_layers

    specs: Params = {
        "embed": ("vocab", "embed_table"),
        "layers": layer_specs,
        "final_norm": ("norm",),
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = ("embed", "vocab")
    return specs


def _block_forward(block_params, x, positions, cfg: DecoderConfig,
                   kv_cache=None, attn_impl="xla", mesh=None,
                   rules=DEFAULT_RULES, prefill=False,
                   expert_axis=None, seq_axis=None, tp_axis=None,
                   valid_len=None, lora=None):
    h = L.rmsnorm(x, block_params["ln1"], cfg, mesh=mesh)
    attn_out, new_cache = L.attention_block(
        block_params["attn"], h, positions, cfg,
        kv_cache=kv_cache, attn_impl=attn_impl, mesh=mesh, prefill=prefill,
        tp_axis=tp_axis, lora=lora)
    # Residual add + second norm as ONE op: fused kernels run it in a
    # single pass over the stream (layers.add_rmsnorm).
    x, h = L.add_rmsnorm(x, attn_out, block_params["ln2"], cfg, mesh=mesh)
    if cfg.is_moe:
        mlp_out, aux = L.moe_block(block_params["mlp"], h, cfg,
                                   expert_axis=expert_axis, seq_axis=seq_axis,
                                   valid_len=valid_len, tp_axis=tp_axis)
    else:
        mlp_out, aux = (L.mlp_block(block_params["mlp"], h, cfg,
                                    tp_axis=tp_axis, mesh=mesh),
                        jnp.float32(0))
    x = x + mlp_out
    if mesh is not None:
        x = with_logical_constraint(x, ("batch", "act_seq", "act_embed"), mesh, rules)
    return x, new_cache, aux


def _remat(fn, policy: str):
    if policy == "none":
        return fn
    if policy == "full":
        return jax.checkpoint(fn, policy=None)
    if policy == "nothing_saveable":
        return jax.checkpoint(
            fn, policy=jax.checkpoint_policies.nothing_saveable)
    if policy == "dots_saveable":
        return jax.checkpoint(fn, policy=jax.checkpoint_policies.dots_saveable)
    if policy == "block_outs":
        # Save post-rope Q/K/V + attention/MLP block outputs (named in
        # models/layers.py) — ~1/4 of dots_no_batch's footprint; backward
        # recomputes only norms, the S×S attention einsums, and the MLP
        # interior.
        return jax.checkpoint(
            fn, policy=jax.checkpoint_policies.save_only_these_names(
                "q_rope", "k_rope", "v_proj", "attn_out", "mlp_out"))
    if policy == "dots_no_batch":
        # The classic transformer policy: save every weight matmul (QKV/out
        # projections, MLP) but recompute the attention einsums — their dots
        # carry batch dims, so the O(S²) score/prob tensors are never stashed.
        return jax.checkpoint(
            fn,
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
    if policy == "dots_flash":
        # dots_no_batch + save the flash kernel's (o, lse): the custom-VJP
        # residuals that dots_no_batch would otherwise rebuild by replaying
        # the forward kernel in the backward. Costs [B,H,S,D] bf16 + lse
        # per layer of HBM; wins when that fits (the headline config's
        # round-4 default — see ops/flash_attention.py note).
        return jax.checkpoint(
            fn,
            policy=jax.checkpoint_policies.save_from_both_policies(
                jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                jax.checkpoint_policies.save_only_these_names(
                    "flash_out", "flash_lse")))
    raise ValueError(f"unknown remat policy {policy!r}")


def decoder_forward(
    params: Params,
    tokens: jax.Array,                 # [B, S] int32
    cfg: DecoderConfig,
    *,
    positions: Optional[jax.Array] = None,
    kv_caches: Optional[dict] = None,  # {"k","v": [L,B,Smax,K,Dh], "len": scalar}
    attn_impl: str = "xla",
    mesh=None,
    rules: LogicalRules = DEFAULT_RULES,
    skip_head: bool = False,
    valid_len: Optional[jax.Array] = None,
    inputs_embeds: Optional[jax.Array] = None,
    lora: Optional[dict] = None,
):
    """Returns (logits [B,S,V] float32, new_kv_caches|None, aux_loss).
    With ``skip_head``, returns the final-norm hidden states [B,S,D] instead
    of logits (the chunked-CE loss applies the head blockwise).
    ``valid_len`` (traced scalar or [B]): marks trailing positions as
    padding for the MoE dispatch path (serving prefill buckets) — see
    layers.moe_block. ``inputs_embeds`` [B,S,D] replaces the embedding
    lookup (pre-scale) — the differentiable-input path attribution
    explainers need (serve/explain.py); ``tokens`` still supplies shapes
    and positions. ``lora`` (multi-tenant serving, serve/lora.py):
    ``{"targets": {t: (a [L,S,din,r], b [L,S,r,dout])}, "aidx": [B],
    "scale": [S]}`` — each row's adapter delta applies inside every
    attention block (rows with aidx = -1 add exact zero)."""
    custom_positions = positions is not None
    if positions is None:
        # Decode with a cache: absolute positions continue from the cache
        # length (RoPE angles and the causal mask must agree on the offset).
        offset = kv_caches["len"] if kv_caches is not None else 0
        positions = jnp.broadcast_to(
            jnp.arange(tokens.shape[1], dtype=jnp.int32)[None, :] + offset,
            tokens.shape)

    dt = cfg.activation_dtype
    table = params["embed"]
    if mesh is not None:
        # The table stores fsdp-sharded on the hidden dim (ZeRO-3); gather
        # that dim explicitly before the token gather (sharding.py rationale
        # at the embed_table rule) — vocab stays model-sharded, the gather
        # of a vocab-sharded operand GSPMD handles natively.
        table = with_logical_constraint(table, ("vocab", None), mesh, rules)
    if inputs_embeds is not None:
        x = inputs_embeds.astype(dt)
    else:
        x = table.astype(dt)[tokens]
    if mesh is not None:
        x = with_logical_constraint(x, ("batch", "act_seq", "act_embed"), mesh, rules)
    if cfg.embed_scale:
        x = x * jnp.asarray(cfg.hidden ** 0.5, dt)

    aux_total = jnp.float32(0)
    new_caches = None
    # Static prefill marker (engine prefill path): cache start is known to be
    # 0 at trace time, enabling the flash kernel. Must never enter a traced
    # pytree (remat would trace it into an array).
    prefill = bool(kv_caches.get("prefill", False)) if kv_caches else False

    pp = dict(mesh.shape).get("pipeline", 1) if mesh is not None else 1
    if pp > 1 and kv_caches is None:
        if custom_positions:
            raise NotImplementedError(
                "pipeline parallelism computes contiguous positions inside "
                "the stage (1F1B streams inexact leaves only); custom "
                "positions are not supported under pp>1")
        # Pipeline parallelism: the layer stack is staged over the
        # ``pipeline`` mesh axis and microbatches stream through via
        # ppermute (parallel/pipeline.py). Decode (kv_caches) stays on the
        # non-pp path — serving shards differently.
        x, aux_total = _pipeline_layers(params["layers"], x, positions, cfg,
                                        mesh, attn_impl)
    elif cfg.scan_layers:
        # Per-layer adapter slices ride the scan xs alongside the layer
        # params (leading L axis); aidx/scale are loop invariants the
        # body closes over (layers.layer_view).
        lora_xs = L.slice_layers(lora)

        def scan_body(carry, scan_in):
            x = carry
            block_params, cache, lora_sl = scan_in
            out, new_cache, aux = _block_forward(
                block_params, x, positions, cfg,
                kv_cache=cache, attn_impl=attn_impl, mesh=mesh, rules=rules,
                prefill=prefill, valid_len=valid_len,
                lora=L.layer_view(lora, lora_sl))
            return out, (new_cache, aux)

        body = _remat(scan_body, cfg.remat_policy)
        if kv_caches is not None:
            # scan consumes the stacked [L, ...] cache leaves alongside params
            def scan_with_cache(carry, scan_in):
                block_params, (ck, cv), lora_sl = scan_in
                cache = {"k": ck, "v": cv, "len": kv_caches["len"]}
                out, (new_cache, aux) = body(
                    carry, (block_params, cache, lora_sl))
                return out, ((new_cache["k"], new_cache["v"]), aux)
            x, ((nk, nv), auxs) = jax.lax.scan(
                scan_with_cache, x,
                (params["layers"], (kv_caches["k"], kv_caches["v"]),
                 lora_xs))
            new_caches = {"k": nk, "v": nv,
                          "len": kv_caches["len"] + tokens.shape[1]}
        else:
            def scan_no_cache(carry, scan_in):
                block_params, lora_sl = scan_in
                out, (_, aux) = body(carry, (block_params, None, lora_sl))
                return out, aux
            x, auxs = jax.lax.scan(scan_no_cache, x,
                                   (params["layers"], lora_xs))
        aux_total = jnp.sum(auxs)
    else:
        per_layer_aux = []
        new_k, new_v = [], []
        block_fn = _remat(
            lambda bp, x, cache, lr: _block_forward(
                bp, x, positions, cfg,
                kv_cache=cache, attn_impl=attn_impl, mesh=mesh, rules=rules,
                prefill=prefill, valid_len=valid_len, lora=lr),
            cfg.remat_policy)
        for i, block_params in enumerate(params["layers"]):
            cache = None
            if kv_caches is not None:
                cache = {"k": kv_caches["k"][i], "v": kv_caches["v"][i],
                         "len": kv_caches["len"]}
            x, new_cache, aux = block_fn(block_params, x, cache,
                                         L.index_layer(lora, i))
            per_layer_aux.append(aux)
            if new_cache is not None:
                new_k.append(new_cache["k"])
                new_v.append(new_cache["v"])
        aux_total = jnp.sum(jnp.stack(per_layer_aux))
        if kv_caches is not None:
            new_caches = {"k": jnp.stack(new_k), "v": jnp.stack(new_v),
                          "len": kv_caches["len"] + tokens.shape[1]}

    x = L.rmsnorm(x, params["final_norm"], cfg, mesh=mesh)
    if skip_head:
        return x, new_caches, aux_total
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("bsd,dv->bsv", x, head.astype(dt),
                        preferred_element_type=jnp.float32)
    if cfg.logits_softcap is not None:
        logits = jnp.tanh(logits / cfg.logits_softcap) * cfg.logits_softcap
    return logits, new_caches, aux_total


def _pipeline_layers(layer_params, x, positions, cfg: DecoderConfig, mesh,
                     attn_impl: str = "xla"):
    """Apply the [L, ...] layer stack as pipeline stages.

    Compositions (the SURVEY.md §2.6 beyond-reference axis):
    - **PP×EP (MoE)**: expert weights keep their ``expert`` sharding inside
      the stage shard_map; each device runs its local experts and psums the
      combined output over the axis (layers.moe_block ``expert_axis``). The
      microbatch-local aux losses stream with the batch and average — the
      standard pipelined-MoE semantics (full-batch fractions aren't visible
      to a microbatch).
    - **PP×SP (ring/Ulysses)**: the streamed activation is additionally
      sharded on the sequence dim over ``seq``; attention runs the
      collective form over that axis inside the stage.
    - **PP×TP**: head/mlp dims keep their Megatron sharding over ``model``
      inside the stage; layers.py runs the output-projection psums (the
      manual form of the GSPMD split the non-pp path derives from rules).
    Positions are computed inside the stage from the seq-shard offset
    (contiguous training positions only — the decode/kv path never takes
    this branch), which keeps every streamed leaf inexact so the 1F1B
    schedule (``cfg.pipeline_schedule``) is legal."""
    from kubeflow_tpu.parallel.pipeline import pipeline_apply
    from jax.sharding import PartitionSpec as P

    axis_sizes = dict(mesh.shape)
    n_stages = axis_sizes["pipeline"]
    sp = (attn_impl in ("ring", "ring_flash", "ulysses")
          and axis_sizes.get("seq", 1) > 1)
    ep = cfg.is_moe and axis_sizes.get("expert", 1) > 1
    tp = axis_sizes.get("model", 1)
    if tp > 1 and (cfg.n_heads % tp or cfg.n_kv_heads % tp
                   or cfg.mlp_dim % tp):
        raise ValueError(
            f"model={tp} must divide n_heads={cfg.n_heads}, "
            f"n_kv_heads={cfg.n_kv_heads} and mlp_dim={cfg.mlp_dim} "
            "for pipeline x TP")
    if cfg.n_layers % n_stages:
        raise ValueError(
            f"pipeline={n_stages} must divide n_layers={cfg.n_layers}")
    per = cfg.n_layers // n_stages
    if not cfg.scan_layers:
        # List-of-blocks layout: stack to the scan layout first.
        from kubeflow_tpu.parallel.pipeline import stack_stage_params

        layer_params = stack_stage_params(layer_params)
    stage_params = jax.tree.map(
        lambda p: p.reshape(n_stages, per, *p.shape[1:]), layer_params)

    # Per-leaf partition specs: stage dim over pipeline; the expert dim keeps
    # its sharding for local-EP compute; head/mlp dims keep their Megatron
    # sharding for in-stage TP (layers.py runs the matching psums).
    # PP×TP×MoE composes the two: experts shard over `expert`, each
    # expert's mlp dim over `model` — one combined psum in the moe block.
    tp_logical = ({"heads", "kv_heads", "mlp", "expert_mlp"}
                  if tp > 1 else set())

    def leaf_spec(spec):
        rest = tuple("expert" if (ep and name == "expert")
                     else "model" if name in tp_logical
                     else None
                     for name in spec)
        return P("pipeline", None, *rest)

    param_specs = jax.tree.map(leaf_spec, _block_specs(cfg),
                               is_leaf=_is_spec_leaf)
    batch_axes = tuple(a for a in ("dcn", "data", "fsdp")
                       if a in mesh.axis_names)
    xs = {"x": x}
    x_specs = {"x": P(batch_axes or None, "seq" if sp else None,
                      *([None] * (x.ndim - 2)))}
    if cfg.is_moe:
        xs["aux"] = jnp.zeros((x.shape[0], 1), jnp.float32)
        x_specs["aux"] = P(batch_axes or None, None)

    impl = {"ring": "ring_local", "ring_flash": "ring_flash_local",
            "ulysses": "ulysses_local"}.get(attn_impl, attn_impl)

    def stage_fn(blocks, xs_mb):
        h = xs_mb["x"]
        s_local = h.shape[1]
        offset = jax.lax.axis_index("seq") * s_local if sp else 0
        pos = jnp.broadcast_to(
            jnp.arange(s_local, dtype=jnp.int32)[None, :] + offset,
            (h.shape[0], s_local))

        def body(h, bp):
            # No logical-constraint mesh inside shard_map: the activation is
            # a local shard there and GSPMD annotations don't apply.
            out, _, aux = _block_forward(
                bp, h, pos, cfg, attn_impl=impl,
                expert_axis="expert" if ep else None,
                seq_axis="seq" if sp else None,
                tp_axis="model" if tp > 1 else None)
            return out, aux

        h, auxs = jax.lax.scan(body, h, blocks)
        out = {"x": h}
        if cfg.is_moe:
            out["aux"] = xs_mb["aux"] + jnp.sum(auxs)
        return out

    out = pipeline_apply(stage_fn, stage_params, xs,
                         mesh=mesh, num_microbatches=None,
                         batch_axes=batch_axes,
                         x_specs=x_specs, param_specs=param_specs,
                         schedule=cfg.pipeline_schedule,
                         # Honor the config's remat knob like the scan path
                         # (_remat); "none" really means no recompute.
                         checkpoint_stages=cfg.remat_policy != "none")
    aux = jnp.mean(out["aux"]) if cfg.is_moe else jnp.float32(0)
    return out["x"], aux


def _chunked_ce(hidden: jax.Array, head: jax.Array, targets: jax.Array,
                cfg: DecoderConfig):
    """Blockwise softmax-CE: scan over sequence chunks so only
    [B, chunk, V] logits are live at once. Under remat the backward
    recomputes per chunk (same O(S·V) flops, O(chunk·V) memory).
    Returns (nll [B,S] f32, correct [B,S] f32)."""
    b, s, d = hidden.shape
    chunk = min(cfg.loss_chunk_size, s)
    if s % chunk:
        chunk = s  # odd tails: fall back to one chunk
    n = s // chunk
    h = hidden.reshape(b, n, chunk, d).swapaxes(0, 1)      # [n,B,c,D]
    t = targets.reshape(b, n, chunk).swapaxes(0, 1)        # [n,B,c]

    @jax.checkpoint
    def body(_, ht):
        hc, tc = ht
        logits = jnp.einsum("bcd,dv->bcv", hc, head,
                            preferred_element_type=jnp.float32)
        if cfg.logits_softcap is not None:
            logits = jnp.tanh(logits / cfg.logits_softcap) * cfg.logits_softcap
        logz = jax.nn.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(logits, tc[..., None], axis=-1)[..., 0]
        correct = (logits.argmax(-1) == tc).astype(jnp.float32)
        return None, (logz - picked, correct)

    _, (nll, correct) = jax.lax.scan(body, None, (h, t))
    return (nll.swapaxes(0, 1).reshape(b, s),
            correct.swapaxes(0, 1).reshape(b, s))


def init_kv_caches(cfg: DecoderConfig, batch: int, max_len: int) -> dict:
    """Contiguous decode cache, stacked over layers."""
    shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, cfg.activation_dtype),
        "v": jnp.zeros(shape, cfg.activation_dtype),
        "len": jnp.int32(0),
    }


def decoder_loss(
    params: Params,
    tokens: jax.Array,        # [B, S+1]: input = [:, :-1], target = [:, 1:]
    cfg: DecoderConfig,
    *,
    loss_mask: Optional[jax.Array] = None,   # [B, S] 1.0 = count this target
    attn_impl: str = "xla",
    mesh=None,
    rules: LogicalRules = DEFAULT_RULES,
    aux_loss_weight: float = 0.01,
):
    """Next-token cross-entropy in fp32. Returns (loss, metrics).

    Loss-path selection, cheapest first: with fused kernels on
    (``cfg.fused_kernels``, layers.fused_kernels_on) the blockwise Pallas
    kernel (ops/fused_xent.py) fuses the output projection, log-softmax
    and NLL — the [B,S,V] logits tensor never exists in HBM, forward OR
    backward. Otherwise ``cfg.loss_chunk_size`` streams the head in
    sequence chunks ([B,chunk,V] live at once), and the dense fallback
    materializes full logits (the usual LLM-training memory hog at large
    vocab)."""
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    fused = L.fused_kernels_on(cfg, mesh)
    if fused:
        from kubeflow_tpu.ops import fused_xent

        fused = fused_xent.supported(
            inputs.shape[0] * inputs.shape[1], cfg.hidden, cfg.vocab_size)
    if fused:
        hidden, _, aux = decoder_forward(
            params, inputs, cfg, attn_impl=attn_impl, mesh=mesh, rules=rules,
            skip_head=True)
        head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
        nll, correct = fused_xent.fused_cross_entropy(
            hidden, head.astype(hidden.dtype), targets,
            logits_softcap=cfg.logits_softcap)
    elif cfg.loss_chunk_size:
        hidden, _, aux = decoder_forward(
            params, inputs, cfg, attn_impl=attn_impl, mesh=mesh, rules=rules,
            skip_head=True)
        head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
        nll, correct = _chunked_ce(hidden, head.astype(hidden.dtype), targets,
                                   cfg)
    else:
        logits, _, aux = decoder_forward(
            params, inputs, cfg, attn_impl=attn_impl, mesh=mesh, rules=rules)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        correct = (logits.argmax(-1) == targets).astype(jnp.float32)
    if loss_mask is None:
        loss_mask = jnp.ones_like(nll)
    denom = jnp.maximum(loss_mask.sum(), 1.0)
    ce = (nll * loss_mask).sum() / denom
    loss = ce + (aux_loss_weight * aux if cfg.is_moe else 0.0)
    metrics = {
        "ce_loss": ce,
        "aux_loss": aux,
        "tokens": denom,
        "accuracy": (correct * loss_mask).sum() / denom,
    }
    return loss, metrics
