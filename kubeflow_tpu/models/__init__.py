"""Functional JAX model zoo: decoder LLMs (Llama/Gemma/Mixtral) and vision
(ViT/CLIP) — the data plane the reference delegates to user containers
(SURVEY.md §1 half 2; BASELINE.json configs).

Design: pure functions over param pytrees (nested dicts), with a parallel
"spec" pytree of logical axis names consumed by `kubeflow_tpu.parallel`.
Layers are stacked and `lax.scan`-ned (compile time O(1) in depth), remat
policies are config-driven, activations run in bfloat16 with fp32 params by
default — the MXU-friendly layout.
"""

from kubeflow_tpu.models.config import DecoderConfig, PRESETS, preset
from kubeflow_tpu.models.decoder import (
    init_decoder_params,
    decoder_param_specs,
    decoder_forward,
    decoder_loss,
)

__all__ = [
    "DecoderConfig",
    "PRESETS",
    "preset",
    "init_decoder_params",
    "decoder_param_specs",
    "decoder_forward",
    "decoder_loss",
]
