"""Decoder model configuration + the preset zoo.

Presets cover the BASELINE.json configs: Llama-3-8B (training + serving
flagship), Gemma-2B (HPO sweeps), Mixtral-8x7B (expert parallel), plus tiny
variants for tests. Architecture facts are from the public model papers/cards.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class DecoderConfig:
    """Hashable (jit-static) decoder architecture description."""

    vocab_size: int = 32000
    hidden: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 8            # < n_heads => GQA
    head_dim: int = 64
    mlp_dim: int = 1408
    max_seq_len: int = 2048
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    hidden_act: str = "silu"       # silu => SwiGLU; gelu => GeGLU (gemma)
    tie_embeddings: bool = False
    norm_plus_one: bool = False    # gemma-style (1 + w) RMSNorm weight
    embed_scale: bool = False      # gemma-style sqrt(hidden) embedding scale
    logits_softcap: Optional[float] = None   # gemma-2 style tanh softcap
    # MoE (0 => dense)
    num_experts: int = 0
    experts_per_token: int = 2
    # "dispatch": capacity-factor top-k routing — only selected experts
    # compute (k/E of dense FLOPs; tokens over a full expert drop).
    # "dense": every expert computes every token, one-hot combine — the
    # FLOP-inefficient but drop-free oracle the dispatch path tests against.
    moe_impl: str = "dispatch"
    # Per-expert buffer size = capacity_factor * k * T / E (rounded up to a
    # multiple of 8 for TPU tiling). 1.0 = perfectly balanced load fits.
    capacity_factor: float = 1.25
    # compile-time policy
    scan_layers: bool = True
    remat_policy: str = "nothing_saveable"   # none | nothing_saveable | full
    # Pipeline-parallel microbatch schedule (only read when the mesh has
    # pipeline>1): "gpipe" | "1f1b" (parallel/pipeline.py).
    pipeline_schedule: str = "gpipe"
    # Sequence-chunked cross-entropy: never materialize [B,S,V] logits
    # (0 = off). Big win at large vocab; numerics identical.
    loss_chunk_size: int = 0
    # Fused Pallas kernels for the non-attention hot ops (ops/fused_xent.py
    # blockwise vocab-chunked CE, ops/fused_norm.py RMSNorm(+residual) and
    # SwiGLU): "auto" = on when the backend is TPU (resolved the same way
    # bench.py resolves attn_impl="pallas"), "on" forces them (interpret
    # mode off-TPU — the CPU parity-test path), "off" keeps the XLA ops.
    # Single-device / per-shard only: under a multi-device GSPMD mesh the
    # kernels fall back (Mosaic can't be auto-partitioned).
    fused_kernels: str = "auto"
    dtype: str = "bfloat16"        # activation/compute dtype
    param_dtype: str = "float32"

    @property
    def q_dim(self) -> int:
        return self.n_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.n_kv_heads * self.head_dim

    @property
    def activation_dtype(self):
        return jnp.dtype(self.dtype)

    @property
    def weight_dtype(self):
        return jnp.dtype(self.param_dtype)

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0

    def num_params(self) -> int:
        """Parameter count (embedding included once if tied)."""
        d, v = self.hidden, self.vocab_size
        attn = d * self.q_dim + 2 * d * self.kv_dim + self.q_dim * d
        if self.is_moe:
            mlp = self.num_experts * 3 * d * self.mlp_dim + d * self.num_experts
        else:
            mlp = 3 * d * self.mlp_dim
        norms = 2 * d
        per_layer = attn + mlp + norms
        embed = v * d if self.tie_embeddings else 2 * v * d
        return self.n_layers * per_layer + embed + d

    def flops_per_token(self) -> float:
        """Approximate training FLOPs/token (fwd+bwd ≈ 6N for dense; MoE
        counts only active experts)."""
        d = self.hidden
        attn = d * self.q_dim + 2 * d * self.kv_dim + self.q_dim * d
        mlp_active = (self.experts_per_token if self.is_moe else 1) * 3 * d * self.mlp_dim
        dense_n = self.n_layers * (attn + mlp_active) + self.vocab_size * d
        return 6.0 * dense_n


PRESETS: dict[str, DecoderConfig] = {
    # Llama-3-8B (public card: 32L, 4096h, 32 heads / 8 kv, 14336 mlp, 128k vocab)
    "llama3-8b": DecoderConfig(
        vocab_size=128256, hidden=4096, n_layers=32, n_heads=32, n_kv_heads=8,
        head_dim=128, mlp_dim=14336, max_seq_len=8192, rope_theta=500000.0,
        loss_chunk_size=512,
    ),
    # Llama-3-70B-class (for sharding dry-runs only)
    "llama3-70b": DecoderConfig(
        vocab_size=128256, hidden=8192, n_layers=80, n_heads=64, n_kv_heads=8,
        head_dim=128, mlp_dim=28672, max_seq_len=8192, rope_theta=500000.0,
        loss_chunk_size=512,
    ),
    # Gemma-2B (public card: 18L, 2048h, 8 heads / 1 kv, head_dim 256, gelu,
    # 256k vocab, tied embeddings, embedding scale, (1+w) norms)
    "gemma-2b": DecoderConfig(
        vocab_size=256128, hidden=2048, n_layers=18, n_heads=8, n_kv_heads=1,
        head_dim=256, mlp_dim=16384, max_seq_len=8192, rope_theta=10000.0,
        hidden_act="gelu", tie_embeddings=True, norm_plus_one=True,
        embed_scale=True, loss_chunk_size=512,
    ),
    # Mixtral-8x7B (public card: 32L, 4096h, 32/8 heads, 14336 mlp, 8 experts top-2)
    "mixtral-8x7b": DecoderConfig(
        vocab_size=32000, hidden=4096, n_layers=32, n_heads=32, n_kv_heads=8,
        head_dim=128, mlp_dim=14336, max_seq_len=8192, rope_theta=1000000.0,
        num_experts=8, experts_per_token=2,
    ),
    # tiny variants for tests/sim (structure-faithful, sized for 1 CPU core)
    "tiny": DecoderConfig(
        vocab_size=256, hidden=64, n_layers=2, n_heads=4, n_kv_heads=2,
        head_dim=16, mlp_dim=128, max_seq_len=128,
    ),
    "tiny-gemma": DecoderConfig(
        vocab_size=256, hidden=64, n_layers=2, n_heads=4, n_kv_heads=1,
        head_dim=16, mlp_dim=128, max_seq_len=128, hidden_act="gelu",
        tie_embeddings=True, norm_plus_one=True, embed_scale=True,
        logits_softcap=30.0,
    ),
    "tiny-moe": DecoderConfig(
        vocab_size=256, hidden=64, n_layers=2, n_heads=4, n_kv_heads=2,
        head_dim=16, mlp_dim=128, max_seq_len=128,
        num_experts=4, experts_per_token=2,
    ),
}


def preset(name: str, **overrides) -> DecoderConfig:
    if name not in PRESETS:
        raise KeyError(f"unknown model preset {name!r}; known: {sorted(PRESETS)}")
    cfg = PRESETS[name]
    return dataclasses.replace(cfg, **overrides) if overrides else cfg
