"""Decoder building blocks: RMSNorm, RoPE, GQA attention, (Swi/Ge)GLU MLP,
MoE block — pure functions over param dicts with logical-axis spec helpers.

Every init returns ``(params, specs)`` where ``specs`` mirrors the param tree
with tuples of logical axis names (consumed by parallel.sharding). Compute
follows the TPU dtype policy: params in ``param_dtype`` (fp32), activations
and matmuls in ``dtype`` (bf16, MXU-native), reductions/softmax/norms in fp32.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from jax.ad_checkpoint import checkpoint_name

from kubeflow_tpu.models.config import DecoderConfig
from kubeflow_tpu.ops.attention import multi_head_attention


def _init(key, shape, dtype, scale: Optional[float] = None):
    """Truncated-normal init with 1/sqrt(fan_in) default scale."""
    fan_in = shape[0] if len(shape) > 1 else shape[-1]
    scale = scale if scale is not None else fan_in ** -0.5
    return (jax.random.truncated_normal(key, -2.0, 2.0, shape, jnp.float32)
            * scale).astype(dtype)


# -- Fused-kernel resolution ---------------------------------------------------

def fused_kernels_on(cfg: DecoderConfig, mesh=None) -> bool:
    """Resolve ``cfg.fused_kernels`` ("auto"|"on"|"off") to a static bool.
    "auto" follows the backend (TPU → Pallas kernels, elsewhere → XLA ops),
    the same resolution rule bench.py applies to ``attn_impl``. A
    multi-device GSPMD mesh disables them: Mosaic kernels cannot be
    auto-partitioned (the flash kernel goes through shard_map instead;
    these run per-shard only where the caller is already inside one)."""
    if mesh is not None and mesh.size > 1:
        return False
    fk = cfg.fused_kernels
    if fk == "on":
        return True
    if fk == "off":
        return False
    if fk != "auto":
        raise ValueError(f"unknown fused_kernels {fk!r} (auto|on|off)")
    return jax.default_backend() == "tpu"


# -- RMSNorm -------------------------------------------------------------------

def init_rmsnorm(cfg: DecoderConfig):
    w = jnp.zeros((cfg.hidden,), cfg.weight_dtype) if cfg.norm_plus_one \
        else jnp.ones((cfg.hidden,), cfg.weight_dtype)
    return w, ("norm",)


def rmsnorm(x: jax.Array, w: jax.Array, cfg: DecoderConfig,
            mesh=None) -> jax.Array:
    if fused_kernels_on(cfg, mesh):
        from kubeflow_tpu.ops import fused_norm

        if fused_norm.norm_supported(x.size // x.shape[-1], x.shape[-1]):
            return fused_norm.rmsnorm_fused(
                x, w, eps=cfg.norm_eps, plus_one=cfg.norm_plus_one)
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    xf = xf * jax.lax.rsqrt(var + cfg.norm_eps)
    wf = (1.0 + w.astype(jnp.float32)) if cfg.norm_plus_one else w.astype(jnp.float32)
    return (xf * wf).astype(x.dtype)


def add_rmsnorm(x: jax.Array, res: jax.Array, w: jax.Array,
                cfg: DecoderConfig, mesh=None):
    """The decoder-block residual idiom ``y = x + res; h = rmsnorm(y)``
    as one op — fused into a single Pallas pass when the kernels are on
    (the stream is read/written once), the two XLA ops otherwise.
    Returns ``(y, h)``."""
    if fused_kernels_on(cfg, mesh):
        from kubeflow_tpu.ops import fused_norm

        if fused_norm.norm_supported(x.size // x.shape[-1], x.shape[-1]):
            return fused_norm.add_rmsnorm_fused(
                x, res, w, eps=cfg.norm_eps, plus_one=cfg.norm_plus_one)
    y = x + res
    return y, rmsnorm(y, w, cfg, mesh)


# -- RoPE ----------------------------------------------------------------------

def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding. x: [B,S,H,D], positions: [B,S] (absolute)."""
    d = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)   # [D/2]
    angles = positions[..., None].astype(jnp.float32) * freqs        # [B,S,D/2]
    cos = jnp.cos(angles)[:, :, None, :]                             # [B,S,1,D/2]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


# -- LoRA (multi-tenant adapters; serve/lora.py owns the registry) -------------

def lora_contrib(h: jax.Array, a_l: jax.Array, b_l: jax.Array,  # traced
                 aidx: jax.Array, scale: jax.Array) -> jax.Array:
    """Batched per-row low-rank update: one gather + two einsums.

    ``h`` [B, S, d_in] (the SAME hidden the base projection consumes);
    ``a_l`` [S_adapters, d_in, r] / ``b_l`` [S_adapters, r, d_out] —
    ONE layer's packed adapter slices; ``aidx`` [B] adapter slot per
    row; ``scale`` [S_adapters]. Rows with ``aidx < 0`` (base traffic)
    multiply by an exact 0.0, so their output is bit-unchanged when the
    result adds onto the base projection. Shapes are fixed by the
    packed buffer, so adapter churn never retraces (the F6xx fixed-
    trace contract)."""
    nslots = a_l.shape[0]
    safe = jnp.clip(aidx, 0, nslots - 1)
    a = a_l[safe]                                 # [B, d_in, r]
    b = b_l[safe]                                 # [B, r, d_out]
    s = scale[safe] * (aidx >= 0)
    t = jnp.einsum("bsd,bdr->bsr", h, a)
    return jnp.einsum("bsr,bro->bso", t, b) * s[:, None, None]


def apply_lora_layer(lora_layer: Optional[dict], target: str,
                     h: jax.Array, base: jax.Array) -> jax.Array:  # traced
    """``base + delta`` for one projection (identity when the layer
    dict is None or the target isn't packed). ``lora_layer`` is
    ``{"targets": {t: (a_l, b_l)}, "aidx": [B], "scale": [S]}`` with
    per-LAYER [S, ...] slices; ``base`` is the projection output in its
    headed shape [B, S, H, Dh] (or [B, S, D] for wo) — the contrib
    reshapes to match."""
    if lora_layer is None or target not in lora_layer["targets"]:
        return base
    a_l, b_l = lora_layer["targets"][target]
    delta = lora_contrib(h, a_l, b_l, lora_layer["aidx"],
                         lora_layer["scale"])
    # The f32 scale promotes the delta; cast back so the cache write /
    # residual keep the activation dtype.
    return base + delta.reshape(base.shape).astype(base.dtype)


def slice_layers(lora: Optional[dict]) -> Optional[dict]:
    """The per-layer scan pytree of a packed-buffer dict: target ->
    (a [L,S,din,r], b [L,S,r,dout]) with the L axis leading, ready to
    be scanned alongside ``params['layers']``. None passes through."""
    if lora is None:
        return None
    return {t: (lora["targets"][t][0], lora["targets"][t][1])
            for t in lora["targets"]}


def layer_view(lora: Optional[dict], scanned_targets: Optional[dict],
               ) -> Optional[dict]:  # traced
    """Rebind one scan step's [S, ...] target slices to the invariant
    aidx/scale operands (closed over by the scan body)."""
    if lora is None:
        return None
    return {"targets": scanned_targets, "aidx": lora["aidx"],
            "scale": lora["scale"]}


def index_layer(lora: Optional[dict], i: int) -> Optional[dict]:
    """Per-layer view for the non-scanned (list-of-blocks) forward."""
    if lora is None:
        return None
    return {"targets": {t: (a[i], b[i])
                        for t, (a, b) in lora["targets"].items()},
            "aidx": lora["aidx"], "scale": lora["scale"]}


# -- Attention block -----------------------------------------------------------

def init_attention(key, cfg: DecoderConfig):
    kq, kk, kv, ko = jax.random.split(key, 4)
    d = cfg.hidden
    params = {
        "wq": _init(kq, (d, cfg.n_heads, cfg.head_dim), cfg.weight_dtype),
        "wk": _init(kk, (d, cfg.n_kv_heads, cfg.head_dim), cfg.weight_dtype),
        "wv": _init(kv, (d, cfg.n_kv_heads, cfg.head_dim), cfg.weight_dtype),
        "wo": _init(ko, (cfg.n_heads, cfg.head_dim, d), cfg.weight_dtype,
                    scale=(cfg.n_heads * cfg.head_dim) ** -0.5),
    }
    specs = {
        "wq": ("embed", "heads", "head_dim"),
        "wk": ("embed", "kv_heads", "head_dim"),
        "wv": ("embed", "kv_heads", "head_dim"),
        "wo": ("heads", "head_dim", "embed"),
    }
    return params, specs


def attention_block(
    p: dict,
    x: jax.Array,                       # [B,S,D]
    positions: jax.Array,               # [B,S]
    cfg: DecoderConfig,
    *,
    kv_cache: Optional[dict] = None,    # {"k","v": [B,Smax,K,Dh]}, + "len": scalar
    attn_impl: str = "xla",
    mesh=None,
    prefill: bool = False,              # static: cache start is known to be 0
    tp_axis: Optional[str] = None,      # inside shard_map: heads sharded here
    lora: Optional[dict] = None,        # per-layer adapter view (apply_lora_layer)
):
    """Returns (out [B,S,D], new_kv_cache|None).

    ``tp_axis`` (Megatron-style TP inside shard_map — the pipeline×TP
    composition): ``wq/wk/wv/wo`` hold this device's head shard, attention
    runs over local heads (heads are independent), and the output
    projection's partial sum psums over the axis — the manual form of the
    split GSPMD derives from the sharding rules outside shard_map."""
    dt = cfg.activation_dtype
    q = jnp.einsum("bsd,dhk->bshk", x, p["wq"].astype(dt))
    k = jnp.einsum("bsd,dhk->bshk", x, p["wk"].astype(dt))
    v = jnp.einsum("bsd,dhk->bshk", x, p["wv"].astype(dt))
    if lora is not None:
        # Multi-tenant adapters: each row's low-rank delta adds onto the
        # shared base projection (gather + two einsums per target; rows
        # with adapter_idx = -1 add an exact zero).
        q = apply_lora_layer(lora, "wq", x, q)
        k = apply_lora_layer(lora, "wk", x, k)
        v = apply_lora_layer(lora, "wv", x, v)
    # Names feed the "block_outs" remat policy: saving post-rope Q/K/V plus
    # the block outputs skips reprojecting + re-rotating in the backward
    # while staying far under dots_no_batch's save footprint.
    q = checkpoint_name(rope(q, positions, cfg.rope_theta), "q_rope")
    k = checkpoint_name(rope(k, positions, cfg.rope_theta), "k_rope")
    v = checkpoint_name(v, "v_proj")

    new_cache = None
    if kv_cache is not None:
        # Contiguous cache decode path: write new K/V at position `len`.
        start = kv_cache["len"]
        ck = jax.lax.dynamic_update_slice_in_dim(kv_cache["k"], k, start, axis=1)
        cv = jax.lax.dynamic_update_slice_in_dim(kv_cache["v"], v, start, axis=1)
        new_cache = {"k": ck, "v": cv, "len": start + x.shape[1]}
        if attn_impl == "pallas" and prefill:
            # Prefill from an empty scratch cache: start is statically 0 and
            # the cache length equals the block, so the flash kernel applies
            # directly (its big win is exactly this forward-only pass).
            # Under a multi-device mesh the kernel must run per-shard
            # (Mosaic can't be GSPMD-partitioned) — the TP serving engine's
            # sharded prefill path; non-dividing shapes fall back to XLA.
            if mesh is not None and mesh.size > 1:
                from kubeflow_tpu.ops.flash_attention import (
                    flash_sharded_or_xla,
                )

                out = flash_sharded_or_xla(q, ck, cv, mesh, causal=True)
            else:
                out = multi_head_attention(q, ck, cv, causal=True, q_offset=0,
                                           impl="pallas")
        else:
            # Decode with a traced cache offset: the masked XLA path (the
            # pallas kernel needs a static q_offset).
            impl = "xla" if attn_impl in ("pallas", "ring", "ring_flash",
                                          "ulysses") else attn_impl
            out = multi_head_attention(
                q, ck, cv, causal=True, q_offset=start, impl=impl,
            )
    elif attn_impl in ("ring", "ring_flash", "ulysses"):
        # Sequence-parallel attention over the mesh 'seq' axis (SURVEY.md
        # §2.6 SP/CP rows). Degenerates to XLA attention when the mesh has
        # no seq sharding (keeps tiny/test configs running unchanged).
        # "ring" resolves its inner block impl by backend (flash kernels on
        # TPU); "ring_flash" forces the kernels (interpret off-TPU) — the
        # dryrun's way of exercising the kernel ring without chips.
        if mesh is None or dict(mesh.shape).get("seq", 1) == 1:
            out = multi_head_attention(q, k, v, causal=True, impl="xla")
        else:
            from kubeflow_tpu.parallel.ring_attention import (
                ring_attention_sharded, ulysses_attention_sharded,
            )

            if attn_impl == "ulysses":
                out = ulysses_attention_sharded(q, k, v, mesh, causal=True)
            else:
                out = ring_attention_sharded(
                    q, k, v, mesh, causal=True,
                    impl="pallas" if attn_impl == "ring_flash" else "auto")
    elif attn_impl in ("ring_local", "ring_flash_local", "ulysses_local"):
        # Already inside shard_map with Q/K/V sharded on dim 1 over 'seq'
        # (the pipeline×SP composition): call the collective form directly.
        from kubeflow_tpu.parallel.ring_attention import (
            ring_attention, ulysses_attention,
        )

        if attn_impl == "ulysses_local":
            out = ulysses_attention(q, k, v, causal=True)
        else:
            out = ring_attention(
                q, k, v, causal=True,
                impl="pallas" if attn_impl == "ring_flash_local" else "auto")
    elif attn_impl == "pallas" and mesh is not None and mesh.size > 1:
        # Mosaic kernels can't be GSPMD-auto-partitioned: run the flash
        # kernel per-shard via shard_map (block-diagonal over batch/heads);
        # shapes that don't shard cleanly fall back to XLA attention.
        from kubeflow_tpu.ops.flash_attention import flash_sharded_or_xla

        out = flash_sharded_or_xla(q, k, v, mesh, causal=True)
    else:
        out = multi_head_attention(q, k, v, causal=True, impl=attn_impl)
    proj = jnp.einsum("bshk,hkd->bsd", out, p["wo"].astype(dt))
    if lora is not None and "wo" in lora["targets"]:
        b, s = out.shape[0], out.shape[1]
        proj = apply_lora_layer(lora, "wo", out.reshape(b, s, -1), proj)
    if tp_axis is not None:
        proj = jax.lax.psum(proj, tp_axis)
    return checkpoint_name(proj, "attn_out"), new_cache


# -- MLP -----------------------------------------------------------------------

def init_mlp(key, cfg: DecoderConfig):
    kg, ku, kd = jax.random.split(key, 3)
    d, m = cfg.hidden, cfg.mlp_dim
    params = {
        "gate": _init(kg, (d, m), cfg.weight_dtype),
        "up": _init(ku, (d, m), cfg.weight_dtype),
        "down": _init(kd, (m, d), cfg.weight_dtype, scale=m ** -0.5),
    }
    specs = {"gate": ("embed", "mlp"), "up": ("embed", "mlp"), "down": ("mlp", "embed")}
    return params, specs


def _act(x: jax.Array, name: str) -> jax.Array:
    if name == "silu":
        return jax.nn.silu(x)
    if name == "gelu":
        return jax.nn.gelu(x, approximate=True)
    raise ValueError(f"unknown activation {name!r}")


def mlp_block(p: dict, x: jax.Array, cfg: DecoderConfig,
              tp_axis: Optional[str] = None, mesh=None) -> jax.Array:
    """``tp_axis``: gate/up hold this device's slice of the mlp dim and
    down's partial products psum over the axis (Megatron MLP split, manual
    form for inside shard_map)."""
    dt = cfg.activation_dtype
    gate_pre = jnp.einsum("bsd,dm->bsm", x, p["gate"].astype(dt))
    up = jnp.einsum("bsd,dm->bsm", x, p["up"].astype(dt))
    h = None
    if fused_kernels_on(cfg, mesh) and cfg.hidden_act in ("silu", "gelu"):
        from kubeflow_tpu.ops import fused_norm

        if fused_norm.norm_supported(up.size // up.shape[-1], up.shape[-1]):
            # One VMEM pass for act(gate) * up; the custom VJP recomputes
            # the activation derivative from (gate, up) instead of stashing
            # act(gate)/sigmoid(gate) intermediates for the backward.
            h = fused_norm.swiglu_fused(gate_pre, up, act=cfg.hidden_act)
    if h is None:
        h = _act(gate_pre, cfg.hidden_act) * up
    out = jnp.einsum("bsm,md->bsd", h, p["down"].astype(dt))
    if tp_axis is not None:
        out = jax.lax.psum(out, tp_axis)
    return checkpoint_name(out, "mlp_out")


# -- MoE -----------------------------------------------------------------------

def init_moe(key, cfg: DecoderConfig):
    kr, kg, ku, kd = jax.random.split(key, 4)
    d, m, e = cfg.hidden, cfg.mlp_dim, cfg.num_experts
    params = {
        "router": _init(kr, (d, e), cfg.weight_dtype),
        "gate": _init(kg, (e, d, m), cfg.weight_dtype, scale=d ** -0.5),
        "up": _init(ku, (e, d, m), cfg.weight_dtype, scale=d ** -0.5),
        "down": _init(kd, (e, m, d), cfg.weight_dtype, scale=m ** -0.5),
    }
    specs = {
        "router": ("embed", None),
        "gate": ("expert", "embed", "expert_mlp"),
        "up": ("expert", "embed", "expert_mlp"),
        "down": ("expert", "expert_mlp", "embed"),
    }
    return params, specs


def moe_block(p: dict, x: jax.Array, cfg: DecoderConfig,
              expert_axis: Optional[str] = None,
              seq_axis: Optional[str] = None,
              valid_len: Optional[jax.Array] = None,
              tp_axis: Optional[str] = None):
    """Top-k MoE (Mixtral semantics: softmax over the selected k logits).

    Dispatches on ``cfg.moe_impl``: "dispatch" (default) routes tokens into
    per-expert capacity buffers so only selected experts compute — k/E of
    the dense FLOPs; "dense" is the drop-free every-expert oracle the
    dispatch path is equivalence-tested against. Returns (out, aux_loss).

    ``valid_len`` (scalar or [B], traced OK): positions >= it are padding
    whose router choices must not claim expert capacity — the serving
    prefill pads prompts to a bucket, and without the mask hundreds of
    identical pad tokens would displace real tokens' choices under
    choice-major priority. Dense ignores it (every expert computes every
    token, pads can't affect real rows).

    ``tp_axis`` (inside shard_map — the PP×TP×MoE composition): weights
    additionally hold this device's slice of the expert-mlp dim (the
    Megatron split applied INSIDE each expert); gate/up produce the local
    m-slice and down's partial products join the expert partials in one
    psum over both axes."""
    if cfg.moe_impl == "dispatch":
        return _moe_dispatch(p, x, cfg, expert_axis=expert_axis,
                             seq_axis=seq_axis, valid_len=valid_len,
                             tp_axis=tp_axis)
    if cfg.moe_impl != "dense":
        raise ValueError(f"unknown moe_impl {cfg.moe_impl!r}")
    return _moe_dense(p, x, cfg, expert_axis=expert_axis, seq_axis=seq_axis,
                      tp_axis=tp_axis)


def _moe_aux_loss(router_logits, onehot_sum, cfg: DecoderConfig,
                  seq_axis: Optional[str], valid=None):
    """Switch-style load-balancing loss: E * sum(frac_tokens * frac_probs).
    ``onehot_sum`` [B,S,E] = how many of the k choices hit each expert.
    ``valid`` [B,S] (optional) masks pad rows out of BOTH fractions and
    renormalizes by the valid-token count — pads route to whatever expert
    the null embedding prefers and would otherwise read as imbalance."""
    probs = jax.nn.softmax(router_logits, axis=-1)                   # [B,S,E]
    if valid is not None:
        # Sum masked numerators and the valid count SEPARATELY across the
        # sequence shards, then divide — pmean of per-shard ratios would
        # weight a shard with 4 valid tokens equally with one holding
        # 1024 (shard-local denominators differ once pads exist).
        m = valid[..., None].astype(probs.dtype)                     # [B,S,1]
        num_t = jnp.sum(onehot_sum * m, axis=(0, 1))                 # [E]
        num_p = jnp.sum(probs * m, axis=(0, 1))                      # [E]
        denom = jnp.sum(m)
        if seq_axis is not None:
            num_t = jax.lax.psum(num_t, seq_axis)
            num_p = jax.lax.psum(num_p, seq_axis)
            denom = jax.lax.psum(denom, seq_axis)
        denom = jnp.maximum(denom, 1.0)
        frac_tokens, frac_probs = num_t / denom, num_p / denom
    else:
        frac_tokens = jnp.mean(onehot_sum, axis=(0, 1))              # [E]
        frac_probs = jnp.mean(probs, axis=(0, 1))                    # [E]
        if seq_axis is not None:   # same denominator on every shard: exact
            frac_tokens = jax.lax.pmean(frac_tokens, seq_axis)
            frac_probs = jax.lax.pmean(frac_probs, seq_axis)
    return cfg.num_experts * jnp.sum(frac_tokens * frac_probs)


def moe_capacity(cfg: DecoderConfig, tokens: int) -> int:
    """Static per-expert buffer size for a ``tokens``-token dispatch:
    ceil(capacity_factor * k * T / E), rounded up to a multiple of 8
    (TPU sublane tiling), capped at k*T (beyond that nothing can drop)."""
    e, k = cfg.num_experts, cfg.experts_per_token
    c = -(-int(cfg.capacity_factor * k * tokens) // e)
    c = -(-max(c, 1) // 8) * 8
    return min(c, k * tokens)


def _moe_dispatch(p: dict, x: jax.Array, cfg: DecoderConfig,
                  expert_axis: Optional[str] = None,
                  seq_axis: Optional[str] = None,
                  valid_len: Optional[jax.Array] = None,
                  tp_axis: Optional[str] = None):
    """Capacity-factor top-k dispatch (SURVEY.md §2.6 EP row: the TPU-native
    MoE data path; (U) training-operator-era Mixtral recipes route via NCCL
    all-to-all — here the routing is scatter/gather into static [E, C]
    buffers and GSPMD/psum provides the cross-device movement).

    - Priority is choice-major: every token's FIRST choice claims capacity
      before any token's second choice (a token never loses its primary
      expert to a neighbor's secondary).
    - A (token, choice) pair over capacity is DROPPED: its combine weight
      contributes nothing (remaining choices are NOT renormalized — Switch/
      Mixtral drop semantics); with capacity_factor >= E/... ample, the
      output matches the dense oracle exactly.
    - Static shapes throughout: C is a compile-time function of T, so one
      trace serves all traffic; the scatter/gather are O(k·T·D) data
      movement instead of the dense path's E/k compute overhead.
    - Capacity is per DISPATCH BATCH: under pipeline microbatching each
      microbatch competes for its own C slots, so drop patterns differ
      from a full-batch run (the standard GPipe×MoE trade) — equivalence
      across schedules holds exactly only when capacity is ample.

    With ``expert_axis`` (inside shard_map): weights hold the local expert
    slice; positions are computed on the replicated router output (identical
    on every shard), each shard scatters/computes only rows routed to its
    local experts, and the combined partial psums over the axis.
    """
    dt = cfg.activation_dtype
    b, s, d = x.shape
    e, k = cfg.num_experts, cfg.experts_per_token
    t = b * s
    xf = x.reshape(t, d)
    router_logits = jnp.einsum(
        "td,de->te", xf, p["router"].astype(dt)).astype(jnp.float32)
    topk_logits, topk_idx = jax.lax.top_k(router_logits, k)          # [T,k]
    topk_w = jax.nn.softmax(topk_logits, axis=-1)                    # [T,k]

    c = moe_capacity(cfg, t)
    # Choice-major flattening: row r = (choice r // T) of token (r % T).
    flat_e = topk_idx.T.reshape(-1)                                  # [kT]
    oh = jax.nn.one_hot(flat_e, e, dtype=jnp.int32)                  # [kT,E]
    valid_flat, valid_bs = None, None
    if valid_len is not None:
        # Padding rows claim no capacity (zeroed before the cumsum), are
        # dropped outright (below), and are masked out of both sides of
        # the balance loss — which otherwise reads a bucket of identical
        # pads as a catastrophically unbalanced router.
        vl = jnp.broadcast_to(jnp.atleast_1d(jnp.asarray(valid_len)), (b,))
        valid_bs = jnp.arange(s)[None, :] < vl[:, None]              # [B,S]
        valid = valid_bs.reshape(t)
        valid_flat = jnp.tile(valid, k)
        oh = oh * valid_flat[:, None].astype(oh.dtype)
    pos = jnp.cumsum(oh, axis=0) - 1
    pos_in_e = jnp.take_along_axis(pos, flat_e[:, None], 1)[:, 0]    # [kT]
    keep = pos_in_e < c
    if valid_flat is not None:
        keep = keep & valid_flat

    e_local, offset = e, 0
    if expert_axis is not None:
        e_local = p["gate"].shape[0]
        offset = jax.lax.axis_index(expert_axis) * e_local
        keep = keep & (flat_e >= offset) & (flat_e < offset + e_local)
    rows = jnp.where(keep, (flat_e - offset) * c + pos_in_e, e_local * c)
    tok_of = jnp.tile(jnp.arange(t), k)                              # [kT]
    # TPU lowers row-granular scatters poorly (measured 2.9× slower than
    # dense!): invert the slot permutation with a SCALAR scatter (cheap),
    # then fill the buffers with a row GATHER — empty slots read OOB and
    # fill with zeros.
    row_of_slot = jnp.full((e_local * c,), t, jnp.int32).at[rows].set(
        tok_of, mode="drop")
    buf = jnp.take(xf, row_of_slot, axis=0, mode="fill",
                   fill_value=0).reshape(e_local, c, d)

    gate = _act(jnp.einsum("ecd,edm->ecm", buf, p["gate"].astype(dt)),
                cfg.hidden_act)
    up = jnp.einsum("ecd,edm->ecm", buf, p["up"].astype(dt))
    y = jnp.einsum("ecm,emd->ecd", gate * up,
                   p["down"].astype(dt)).reshape(e_local * c, d)

    back = jnp.take(y, rows, axis=0, mode="fill", fill_value=0)      # [kT,D]
    w_flat = topk_w.T.reshape(-1, 1).astype(dt)
    out = (back * w_flat).reshape(k, t, d).sum(0).reshape(b, s, d)
    # One combined reduction: expert partials (each shard computed its
    # local experts) and Megatron partials (down contracted a local
    # m-slice) sum over both axes at once.
    axes = tuple(a for a in (expert_axis, tp_axis) if a is not None)
    if axes:
        out = jax.lax.psum(out, axes)

    aux = _moe_aux_loss(
        router_logits.reshape(b, s, e),
        oh.astype(jnp.float32).reshape(k, t, e).sum(0).reshape(b, s, e),
        cfg, seq_axis, valid=valid_bs)
    return checkpoint_name(out, "mlp_out"), aux


def _moe_dense(p: dict, x: jax.Array, cfg: DecoderConfig,
               expert_axis: Optional[str] = None,
               seq_axis: Optional[str] = None,
               tp_axis: Optional[str] = None):
    """Einsum-dense formulation: every expert computes every token and a
    one-hot combine weights the results. FLOP-inefficient (E/k overcompute)
    but fully static-shaped and drop-free — under GSPMD the ``expert``
    sharding of the weight specs turns the expert einsums into
    expert-parallel partials XLA combines; serves as the dispatch path's
    correctness oracle.

    With ``expert_axis`` (inside shard_map — the pipeline×EP composition),
    ``p["gate"]/["up"]/["down"]`` hold this device's expert slice: the block
    computes local experts only, slices the combine weights at the shard
    offset, and psums the combined output over the axis. The router is
    replicated, so top-k runs on full logits. ``seq_axis`` (sequence-sharded
    activations, PP×SP): the load-balancing fractions pmean over the axis so
    the aux loss sees full-sequence statistics."""
    dt = cfg.activation_dtype
    e, k = cfg.num_experts, cfg.experts_per_token
    router_logits = jnp.einsum("bsd,de->bse", x, p["router"].astype(dt)).astype(jnp.float32)
    topk_logits, topk_idx = jax.lax.top_k(router_logits, k)          # [B,S,k]
    topk_w = jax.nn.softmax(topk_logits, axis=-1)                    # mixtral: softmax over top-k
    onehot = jax.nn.one_hot(topk_idx, e, dtype=jnp.float32)          # [B,S,k,E]
    combine = jnp.einsum("bske,bsk->bse", onehot, topk_w)            # [B,S,E]

    if expert_axis is not None:
        e_local = p["gate"].shape[0]
        offset = jax.lax.axis_index(expert_axis) * e_local
        combine = jax.lax.dynamic_slice_in_dim(combine, offset, e_local,
                                               axis=-1)
    gate = _act(jnp.einsum("bsd,edm->ebsm", x, p["gate"].astype(dt)), cfg.hidden_act)
    up = jnp.einsum("bsd,edm->ebsm", x, p["up"].astype(dt))
    expert_out = jnp.einsum("ebsm,emd->ebsd", gate * up, p["down"].astype(dt))
    out = jnp.einsum("ebsd,bse->bsd", expert_out, combine.astype(dt))
    axes = tuple(a for a in (expert_axis, tp_axis) if a is not None)
    if axes:
        out = jax.lax.psum(out, axes)

    aux = _moe_aux_loss(router_logits, onehot.sum(axis=2), cfg, seq_axis)
    return out, aux


# -- Embedding -----------------------------------------------------------------

def init_embedding(key, cfg: DecoderConfig):
    tok = _init(key, (cfg.vocab_size, cfg.hidden), cfg.weight_dtype, scale=1.0)
    return tok, ("vocab", "embed_table")
