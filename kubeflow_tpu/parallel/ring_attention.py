"""Sequence/context parallelism: ring attention + Ulysses head-swap.

The reference platform has NO long-context support — sequence length is the
workload's problem (SURVEY.md §5 'Long-context / sequence parallelism:
absent'). Here it is first-class (§2.6 rows SP/CP/ring/Ulysses):

- **Ring attention** (`ring_attention`): Q/K/V sharded on the sequence dim
  over the ``seq`` mesh axis; each step computes blockwise attention against
  the resident KV shard while `lax.ppermute` rotates KV around the ICI ring,
  accumulating the exact softmax online (m/l/acc rescaling — the blockwise
  attention recurrence). XLA overlaps the ppermute with the block compute;
  memory per chip stays O(S/n · S/n) per step instead of O(S²).
- **Ulysses** (`ulysses_attention`): `lax.all_to_all` swaps the sequence
  sharding for a head sharding, runs ordinary full attention locally (any
  impl, incl. the Pallas flash kernel), and swaps back — cheaper at moderate
  S when heads ≥ ring size.

The ring's per-step block math has two impls: ``impl="pallas"`` runs the
tuned flash kernels per KV shard (the S=2048-headline retune — bf16 MXU
inputs, fp32 softmax stats — applied at ring scale, where long-context
actually lives) under a hand-written custom_vjp whose backward is a second
ring rotating dK/dV accumulators with the KV shards; ``impl="xla"`` keeps
the einsum/scan online-softmax as the anywhere-runnable numerics oracle.
The traced ring offset never reaches a kernel: for causal attention the
(q_shard, kv_shard) relation is one of three STATIC cases — fully visible
(past shards), the causal diagonal, fully masked (future) — picked by
``lax.switch``, so each branch calls the kernel with a static causal flag
and q_offset=0, and the masked branch skips the matmul entirely.

Both schedules are differentiable (the XLA path by construction —
scan/ppermute/all_to_all have transposes — and the Pallas path via its
custom ring VJP), so the same code serves training and inference. Call them
inside ``shard_map`` (the model does), or use the ``*_sharded`` wrappers.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from kubeflow_tpu.compat import axis_size as _axis_size, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from kubeflow_tpu.ops.attention import NEG_INF, _repeat_kv


def _block_attn_step(q, k, v, m, l, acc, *, q_start, kv_start, causal,
                     sm_scale, softcap):
    """One online-softmax accumulation step of local Q against one KV shard.

    q: [B,Sq,H,D]; k/v: [B,Skv,H,D]; m/l: [B,H,Sq]; acc: [B,Sq,H,D] (f32).
    ``q_start``/``kv_start`` are global offsets (traced OK)."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * sm_scale
    if softcap is not None:
        s = jnp.tanh(s / softcap) * softcap
    sq, skv = q.shape[1], k.shape[1]
    if causal:
        q_pos = q_start + jnp.arange(sq)[:, None]
        kv_pos = kv_start + jnp.arange(skv)[None, :]
        mask = kv_pos <= q_pos                     # [Sq, Skv]
        s = jnp.where(mask[None, None], s, NEG_INF)
    m_cur = jnp.max(s, axis=-1)                    # [B,H,Sq]
    m_new = jnp.maximum(m, m_cur)
    # exp(NEG_INF - NEG_INF) would be 1: zero fully-masked entries explicitly.
    p = jnp.exp(s - m_new[..., None])
    p = jnp.where(s <= NEG_INF / 2, 0.0, p)
    alpha = jnp.exp(m - m_new)                     # [B,H,Sq]
    l_new = l * alpha + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32),
                    preferred_element_type=jnp.float32)
    acc_new = acc * jnp.transpose(alpha, (0, 2, 1))[..., None] + pv
    return m_new, l_new, acc_new


def _ring_merge(o_acc, lse_acc, o_t, lse_t):
    """Merge a new normalized partial (o_t, lse_t) into the running one.

    Both partials are softmax-normalized over their own key sets; the
    unnormalized sums are exp(lse)·o, so the merge is the usual max-rescaled
    combine. A fully-masked partial carries lse = NEG_INF and contributes
    exp(NEG_INF − m) = 0; when BOTH sides are masked the denominator is 2
    with zero numerators — still exact zeros, no special case."""
    m = jnp.maximum(lse_acc, lse_t)
    a = jnp.exp(lse_acc - m)                       # [B,H,Sq]
    b = jnp.exp(lse_t - m)
    denom = a + b
    o_new = (a[..., None] * o_acc
             + b[..., None] * o_t.astype(jnp.float32)) / denom[..., None]
    return o_new, m + jnp.log(denom)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _ring_flash(q, k, v, axis_name, causal, sm_scale, softcap, interpret):
    out, _ = _ring_flash_fwd_impl(q, k, v, axis_name, causal, sm_scale,
                                  softcap, interpret)
    return out


def _ring_flash_fwd_impl(q, k, v, axis_name, causal, sm_scale, softcap,
                         interpret):
    from kubeflow_tpu.ops.flash_attention import _flash_fwd

    n = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    qt = jnp.swapaxes(q, 1, 2)                     # [B,H,Sq,D]
    kt = jnp.swapaxes(k, 1, 2)                     # [B,KH,Skv,D] (raw GQA)
    vt = jnp.swapaxes(v, 1, 2)
    b, h, sq, d = qt.shape
    perm = [(i, (i + 1) % n) for i in range(n)]

    def visible(args):                             # past shard: no mask
        k_c, v_c = args
        return _flash_fwd(qt, k_c, v_c, causal=False, sm_scale=sm_scale,
                          softcap=softcap, q_offset=0, block_q=None,
                          block_kv=None, interpret=interpret)

    def diagonal(args):                            # own shard: square causal
        k_c, v_c = args
        return _flash_fwd(qt, k_c, v_c, causal=True, sm_scale=sm_scale,
                          softcap=softcap, q_offset=0, block_q=None,
                          block_kv=None, interpret=interpret)

    def masked(args):                              # future shard: skip
        return (jnp.zeros((b, h, sq, d), qt.dtype),
                jnp.full((b, h, sq), NEG_INF, jnp.float32))

    def step(carry, t):
        k_c, v_c, o_acc, lse_acc = carry
        shard = (idx - t) % n
        if causal:
            case = jnp.where(shard == idx, 1, jnp.where(shard < idx, 0, 2))
            o_t, lse_t = jax.lax.switch(case, [visible, diagonal, masked],
                                        (k_c, v_c))
        else:
            o_t, lse_t = visible((k_c, v_c))
        o_acc, lse_acc = _ring_merge(o_acc, lse_acc, o_t, lse_t)
        k_nxt = jax.lax.ppermute(k_c, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_c, axis_name, perm)
        return (k_nxt, v_nxt, o_acc, lse_acc), None

    o0 = jnp.zeros((b, h, sq, d), jnp.float32)
    lse0 = jnp.full((b, h, sq), NEG_INF, jnp.float32)
    (_, _, o_acc, lse), _ = jax.lax.scan(step, (kt, vt, o0, lse0),
                                         jnp.arange(n))
    return jnp.swapaxes(o_acc.astype(q.dtype), 1, 2), lse


def _ring_flash_vjp_fwd(q, k, v, axis_name, causal, sm_scale, softcap,
                        interpret):
    out, lse = _ring_flash_fwd_impl(q, k, v, axis_name, causal, sm_scale,
                                    softcap, interpret)
    return out, (q, k, v, out, lse)


def _ring_flash_vjp_bwd(axis_name, causal, sm_scale, softcap, interpret,
                        res, do):
    """The backward ring: dK/dV accumulators travel WITH their KV shard (n
    rotations return both to the home device), dQ accumulates locally. Each
    step calls the flash backward kernels with the GLOBAL lse/delta, which
    makes per-shard contributions exact — the same property that lets the
    single-chip VJP be one recompute sweep."""
    from kubeflow_tpu.ops.flash_attention import _flash_bwd_pallas

    q, k, v, out, lse = res
    n = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    ot = jnp.swapaxes(out, 1, 2)
    dot_ = jnp.swapaxes(do, 1, 2)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def grads(k_c, v_c, diag):
        return _flash_bwd_pallas(
            qt, k_c, v_c, ot, lse, dot_, causal=diag, sm_scale=sm_scale,
            softcap=softcap, q_offset=0, block_q=None, block_kv=None,
            interpret=interpret)

    def visible(args):
        return grads(args[0], args[1], False)

    def diagonal(args):
        return grads(args[0], args[1], True)

    def masked(args):
        k_c, v_c = args
        return (jnp.zeros_like(qt), jnp.zeros_like(k_c),
                jnp.zeros_like(v_c))

    def step(carry, t):
        k_c, v_c, dk_c, dv_c, dq_acc = carry
        shard = (idx - t) % n
        if causal:
            case = jnp.where(shard == idx, 1, jnp.where(shard < idx, 0, 2))
            dq_t, dk_t, dv_t = jax.lax.switch(
                case, [visible, diagonal, masked], (k_c, v_c))
        else:
            dq_t, dk_t, dv_t = visible((k_c, v_c))
        dq_acc = dq_acc + dq_t.astype(jnp.float32)
        dk_c = dk_c + dk_t.astype(jnp.float32)
        dv_c = dv_c + dv_t.astype(jnp.float32)
        # Rotate the shard and its gradient accumulator together; fp32
        # accumulators double the backward's ring traffic vs the bf16 KV —
        # the price of exact accumulation across n partial sums.
        k_c, v_c, dk_c, dv_c = (jax.lax.ppermute(x, axis_name, perm)
                                for x in (k_c, v_c, dk_c, dv_c))
        return (k_c, v_c, dk_c, dv_c, dq_acc), None

    dk0 = jnp.zeros(kt.shape, jnp.float32)
    dv0 = jnp.zeros(vt.shape, jnp.float32)
    dq0 = jnp.zeros(qt.shape, jnp.float32)
    (_, _, dk, dv, dq), _ = jax.lax.scan(
        step, (kt, vt, dk0, dv0, dq0), jnp.arange(n))
    return (jnp.swapaxes(dq, 1, 2).astype(q.dtype),
            jnp.swapaxes(dk, 1, 2).astype(k.dtype),
            jnp.swapaxes(dv, 1, 2).astype(v.dtype))


_ring_flash.defvjp(_ring_flash_vjp_fwd, _ring_flash_vjp_bwd)


def ring_attention(
    q: jax.Array,                     # [B, S_local, H, D] (seq shard)
    k: jax.Array,                     # [B, S_local, K, D]
    v: jax.Array,                     # [B, S_local, K, D]
    *,
    axis_name: str = "seq",
    causal: bool = True,
    sm_scale: Optional[float] = None,
    logits_softcap: Optional[float] = None,
    impl: str = "auto",
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Exact attention over the full (ring-distributed) sequence. Must run
    inside shard_map with q/k/v sharded on dim 1 over ``axis_name``.

    ``impl``: "pallas" runs the tuned flash kernels per KV shard (custom
    ring VJP); "xla" is the einsum/scan oracle; "auto" picks pallas on TPU.
    """
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl == "pallas":
        scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
        return _ring_flash(q, k, v, axis_name, causal, scale,
                           logits_softcap, interpret)
    if impl != "xla":
        raise ValueError(f"unknown ring attention impl {impl!r}")
    n = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    # GQA expansion happens per-step inside _block_attn_step: the ring
    # rotates the RAW [B,S,K,D] shards, so ppermute traffic and the scan
    # carry stay 1/n_rep the size of the expanded heads.
    n_rep = h // k.shape[2]
    scale = sm_scale if sm_scale is not None else d ** -0.5

    q_start = idx * s_local
    m0 = jnp.full((b, h, s_local), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_local), jnp.float32)
    acc0 = jnp.zeros((b, s_local, h, d), jnp.float32)

    # Ring schedule: at step t this device holds KV shard (idx - t) mod n and
    # passes it on to rank+1 afterwards, so every device sees every shard.
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, t):
        k_cur, v_cur, m, l, acc = carry
        kv_shard = (idx - t) % n
        m, l, acc = _block_attn_step(
            q, _repeat_kv(k_cur, n_rep), _repeat_kv(v_cur, n_rep), m, l, acc,
            q_start=q_start, kv_start=kv_shard * s_local,
            causal=causal, sm_scale=scale, softcap=logits_softcap)
        # Rotate KV for the next step (skipped result after the last one is
        # harmless; XLA overlaps this transfer with the next block compute).
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, m, l, acc), None

    (_, _, m, l, acc), _ = jax.lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(n))
    l_t = jnp.transpose(l, (0, 2, 1))[..., None]   # [B,Sq,H,1]
    out = acc / jnp.where(l_t == 0.0, 1.0, l_t)
    return out.astype(q.dtype)


def ulysses_attention(
    q: jax.Array,                     # [B, S_local, H, D]
    k: jax.Array,                     # [B, S_local, K, D]
    v: jax.Array,                     # [B, S_local, K, D]
    *,
    axis_name: str = "seq",
    causal: bool = True,
    sm_scale: Optional[float] = None,
    logits_softcap: Optional[float] = None,
    impl: str = "xla",
) -> jax.Array:
    """All-to-all swap seq-sharding → head-sharding, local full attention,
    swap back (the DeepSpeed-Ulysses schedule, TPU-natively over ICI)."""
    from kubeflow_tpu.ops.attention import multi_head_attention

    n = _axis_size(axis_name)
    h, kh = q.shape[2], k.shape[2]
    if h % n or kh % n:
        raise ValueError(
            f"ulysses needs heads divisible by the seq axis: H={h}, K={kh}, "
            f"axis={n} (use ring attention otherwise)")
    # [B, S/n, H, D] -> [B, S, H/n, D]
    qh = jax.lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1,
                            tiled=True)
    kh_ = jax.lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1,
                             tiled=True)
    vh = jax.lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1,
                            tiled=True)
    out = multi_head_attention(qh, kh_, vh, causal=causal,
                               logits_softcap=logits_softcap, impl=impl)
    # [B, S, H/n, D] -> [B, S/n, H, D]
    return jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)


def _sharded(fn, mesh: Mesh, axis_name: str, batch_axes):
    spec = P(batch_axes, axis_name, None, None)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)


def ring_attention_sharded(
    q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh, *,
    axis_name: str = "seq", batch_axes=("dcn", "data", "fsdp"),
    causal: bool = True, sm_scale: Optional[float] = None,
    logits_softcap: Optional[float] = None,
    impl: str = "auto", interpret: Optional[bool] = None,
) -> jax.Array:
    """Convenience wrapper: applies shard_map over the mesh (batch sharded on
    the data axes, sequence on ``axis_name``)."""
    batch = tuple(a for a in batch_axes if a in mesh.axis_names)
    fn = functools.partial(ring_attention, axis_name=axis_name, causal=causal,
                           sm_scale=sm_scale, logits_softcap=logits_softcap,
                           impl=impl, interpret=interpret)
    return _sharded(fn, mesh, axis_name, batch)(q, k, v)


def ulysses_attention_sharded(
    q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh, *,
    axis_name: str = "seq", batch_axes=("dcn", "data", "fsdp"),
    causal: bool = True, sm_scale: Optional[float] = None,
    logits_softcap: Optional[float] = None, impl: str = "xla",
) -> jax.Array:
    batch = tuple(a for a in batch_axes if a in mesh.axis_names)
    fn = functools.partial(ulysses_attention, axis_name=axis_name,
                           causal=causal, sm_scale=sm_scale,
                           logits_softcap=logits_softcap, impl=impl)
    return _sharded(fn, mesh, axis_name, batch)(q, k, v)
