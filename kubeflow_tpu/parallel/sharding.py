"""Logical-axis sharding rules.

Models annotate every parameter/activation with *logical* axis names; a rule
table maps logical names → mesh axes (or None = replicated). Changing the
parallelism strategy (pure DP ↔ FSDP ↔ FSDP+TP ↔ +EP/SP) is a rule-table
change, not a model change — the TPU-native idiom (GSPMD partitioning; cf.
the public MaxText/flax logical-partitioning pattern), replacing the
reference's per-framework launcher plumbing.

Default rule intent:
- ``batch``      → sharded over all data-parallel axes (dcn, data, fsdp)
- ``embed``      → FSDP-sharded (params' model dim over fsdp; ZeRO-3 analog)
- ``heads/mlp/kv/vocab`` → tensor-parallel over ``model`` (Megatron splits)
- ``expert``     → expert-parallel over ``expert``
- ``act_seq``    → sequence-parallel over ``seq`` (ring attention)
- ``layers``     → replicated (the scan axis)
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

Axis = Optional[Union[str, tuple[str, ...]]]
LogicalRules = tuple[tuple[str, Axis], ...]

DEFAULT_RULES: LogicalRules = (
    ("batch", ("dcn", "data", "fsdp")),
    ("act_seq", "seq"),
    ("act_embed", None),
    ("embed", "fsdp"),
    ("heads", "model"),
    ("kv_heads", "model"),
    ("head_dim", None),
    ("mlp", "model"),
    ("vocab", "model"),
    # The embedding table stores vocab-parallel (Megatron) over ``model`` AND
    # ZeRO-3-sharded over ``fsdp`` on the hidden dim. The token gather can't
    # consume an fsdp-sharded operand (its output spec would reuse fsdp,
    # already consumed by the batch dim — GSPMD rejects the reuse), so the
    # forward all-gathers the hidden dim explicitly first
    # (decoder_forward's with_logical_constraint(("vocab", None))); the
    # transpose reduce-scatters the table grad back. Storage per chip drops
    # by the fsdp degree — the difference between replicating GBs of a
    # 128k-vocab table and not.
    ("embed_table", "fsdp"),
    ("expert", "expert"),
    ("expert_mlp", "model"),
    ("layers", None),
    ("stage", "pipeline"),
    ("norm", None),
)


def with_rule(rules: LogicalRules, name: str, axis: Axis) -> LogicalRules:
    """A copy of ``rules`` with one mapping replaced (e.g. layers→pipeline
    when pipeline parallelism shards the layer stack across stages)."""
    return tuple((n, axis if n == name else a) for n, a in rules)


def logical_to_mesh_axes(
    logical_axes: Sequence[Optional[str]],
    rules: LogicalRules = DEFAULT_RULES,
) -> PartitionSpec:
    """Map a tuple of logical axis names to a PartitionSpec via the rules.

    A mesh axis may be used at most once in a spec (GSPMD constraint): later
    logical axes that would reuse an already-consumed mesh axis fall back to
    replication on that axis."""
    table = dict(rules)
    used: set[str] = set()
    out = []
    for name in logical_axes:
        if name is None:
            out.append(None)
            continue
        if name not in table:
            raise KeyError(f"no sharding rule for logical axis {name!r}")
        mesh_axes = table[name]
        if mesh_axes is None:
            out.append(None)
            continue
        if isinstance(mesh_axes, str):
            mesh_axes = (mesh_axes,)
        free = tuple(a for a in mesh_axes if a not in used)
        used.update(free)
        if not free:
            out.append(None)
        elif len(free) == 1:
            out.append(free[0])
        else:
            out.append(free)
    return PartitionSpec(*out)


def named_sharding(
    mesh: Mesh,
    logical_axes: Sequence[Optional[str]],
    rules: LogicalRules = DEFAULT_RULES,
) -> NamedSharding:
    return NamedSharding(mesh, logical_to_mesh_axes(logical_axes, rules))


def _is_spec_leaf(x: Any) -> bool:
    return isinstance(x, tuple) and all(isinstance(e, (str, type(None))) for e in x)


def _drop_nondivisible(spec: PartitionSpec, shape: tuple[int, ...],
                       mesh: Mesh) -> PartitionSpec:
    """Replicate any dim whose size isn't divisible by its mesh-axis product
    (e.g. 2 GQA kv heads under model=4 tensor parallelism)."""
    out = []
    for dim, axes in zip(shape, tuple(spec) + (None,) * (len(shape) - len(spec))):
        if axes is None:
            out.append(None)
            continue
        axes_t = (axes,) if isinstance(axes, str) else tuple(axes)
        degree = 1
        for a in axes_t:
            degree *= mesh.shape[a]
        out.append(axes if degree > 0 and dim % degree == 0 else None)
    return PartitionSpec(*out)


def shard_params(params: Any, specs: Any, mesh: Mesh,
                 rules: LogicalRules = DEFAULT_RULES) -> Any:
    """Build a NamedSharding pytree matching ``params`` from the parallel
    ``specs`` pytree of logical-axis tuples.

    ``params`` may be real arrays, ShapeDtypeStructs, or None. When shapes
    are available, dims that don't divide their mesh-axis product are
    replicated instead of erroring."""
    if params is None:
        return jax.tree.map(
            lambda spec: named_sharding(mesh, spec, rules),
            specs, is_leaf=_is_spec_leaf)

    def one(spec, leaf):
        ps = logical_to_mesh_axes(spec, rules)
        from kubeflow_tpu.ops.quantization import QuantizedTensor

        if isinstance(leaf, QuantizedTensor):
            # int8 serving weights: q keeps the weight's shape and takes its
            # spec; the scale's collapsed contraction dims (size 1) must not
            # inherit a sharded axis — per-field drop handles both.
            return QuantizedTensor(
                q=NamedSharding(mesh, _drop_nondivisible(
                    ps, tuple(leaf.q.shape), mesh)),
                scale=NamedSharding(mesh, _drop_nondivisible(
                    ps, tuple(leaf.scale.shape), mesh)))
        ps = _drop_nondivisible(ps, tuple(leaf.shape), mesh)
        return NamedSharding(mesh, ps)

    # specs first: is_leaf must stop descent at the spec tuples.
    return jax.tree.map(
        one, specs, params,
        is_leaf=_is_spec_leaf)


def with_logical_constraint(
    x: jax.Array,
    logical_axes: Sequence[Optional[str]],
    mesh: Optional[Mesh] = None,
    rules: LogicalRules = DEFAULT_RULES,
) -> jax.Array:
    """`with_sharding_constraint` in logical-axis terms. Inside jit under a
    mesh context the mesh is implicit; no-op when no mesh is active."""
    spec = logical_to_mesh_axes(logical_axes, rules)
    if mesh is not None:
        # Explicit mesh: a failure here is a real annotation bug — propagate.
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
    try:
        return jax.lax.with_sharding_constraint(x, spec)
    except (ValueError, RuntimeError):
        # No mesh context (e.g. single-device eager) — constraint is advisory.
        return x
