"""Pipeline parallelism: microbatch streaming over the ``pipeline`` mesh axis.

The reference has no pipeline parallelism (SURVEY.md §2.6: 'not implemented'
— user images bring Megatron/DeepSpeed). TPU-natively, stages live on
ICI-neighbor devices and activations hop stage→stage with `lax.ppermute`
inside `shard_map` — the collective-pipelining recipe (cf. the public
scaling-book/praxis pattern), not an NCCL p2p translation.

Two schedules:

- **GPipe** — m microbatches through n stages in m+n-1 ticks; at tick t
  stage s runs microbatch t-s (bubble ticks are masked compute, fraction
  (n-1)/(m+n-1)). The whole schedule is a `lax.scan`, so it jits once and
  differentiates (reverse-mode produces the mirrored backward pipeline).
  Autodiff stashes one boundary activation per microbatch per stage, so m
  is capped at 2·stages — bubble floor ≈ ⅓.
- **1F1B** (``schedule="1f1b"``) — the forward is the same streaming scan,
  but the backward is a hand-written interleaved schedule (custom_vjp): per
  super-tick each stage runs one forward (recompute) and one backward of an
  *earlier* microbatch, with activations hopping forward and cotangents
  hopping backward in the same tick. Live stage-inputs are bounded by a
  ring buffer of depth 2n-1 — **independent of m** — so microbatch count
  (and thus bubble fraction (n-1)/(m+n-1)) is no longer memory-capped.
  FLOPs: 3 forwards + 1 backward per microbatch per stage (the fwd lane
  regenerates ring inputs and the vjp's primal re-runs the stage), ~25%
  over checkpointed GPipe's 2 fwd + 1 bwd — the price of the
  m-independent ring.

Both compose with the data axes in the same mesh (``batch_axes`` shards the
batch dim of the streamed pytree). Stage weights: leading dim sharded over
``pipeline``.

Composition beyond data axes goes through ``x_specs`` / ``param_specs``:
callers may shard additional dims of the streamed pytree (e.g. the sequence
dim over ``seq`` for PP×SP ring attention) or of the stage params (e.g. the
expert dim over ``expert`` for PP×EP MoE), and run the matching collectives
inside ``stage_fn`` — every mesh axis is a named collective axis inside the
worker. The GPipe schedule differentiates through shard_map (psums for
replicated operands are inserted by the transpose); the hand-written 1F1B
backward derives its gradient-sync psums from the specs: parameter grads
psum over every axis the streamed pytree is sharded on but the param is not,
and input cotangents psum over every axis the params are sharded on (minus
the pipeline axis itself) but the stream is not.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from kubeflow_tpu.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

StageFn = Callable[[Any, Any], Any]


def stack_stage_params(per_stage: list[Any]) -> Any:
    """[stage0_tree, stage1_tree, ...] → one tree with leading stage dim."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage)


def pipeline_apply(
    stage_fn: StageFn,
    stage_params: Any,                # leaves [n_stages, ...], pipeline-sharded
    xs: Any,                          # pytree; every leaf [batch, ...]
    *,
    mesh: Mesh,
    num_microbatches: int | None = None,
    axis_name: str = "pipeline",
    batch_axes: tuple = ("dcn", "data", "fsdp"),
    checkpoint_stages: bool = True,
    schedule: str = "gpipe",
    x_specs: Any = None,              # pytree of PartitionSpec matching xs
    param_specs: Any = None,          # pytree of PartitionSpec, dim0=pipeline
) -> Any:
    """Run ``y = stage_{n-1}(... stage_0(xs))`` pipelined over microbatches.

    ``stage_fn(params_one_stage, xs_mb) -> ys_mb`` must preserve the pytree
    structure and leaf shapes (the transformer-stack contract). Every leaf
    streams with the microbatch; the batch dim may additionally be sharded
    over ``batch_axes``. With ``schedule="gpipe"``, ``num_microbatches=None``
    auto-picks the largest m ≤ 2·stages dividing the local batch (autodiff
    stashes per-microbatch activations — bubble ≤ ⅓); with ``"1f1b"`` the
    stash is a fixed 2n-1 ring so auto-m rises to ≤ 4·stages and any m is
    legal (every leaf must then be inexact — stream ints via closure).
    Returns the same pytree, [batch, ...] per leaf."""
    n_stages = mesh.shape[axis_name]
    leaves = jax.tree.leaves(xs)
    batch = leaves[0].shape[0]
    data_shards = 1
    batch_axes = tuple(a for a in batch_axes if a in mesh.axis_names)
    for a in batch_axes:
        data_shards *= mesh.shape[a]
    local_batch = batch // data_shards
    if num_microbatches is None:
        m_cap = (4 if schedule == "1f1b" else 2) * n_stages
        num_microbatches = next(
            (m for m in range(min(m_cap, max(local_batch, 1)), 0, -1)
             if local_batch % m == 0), 1)
    if batch % data_shards or local_batch % num_microbatches:
        raise ValueError(
            f"batch {batch} must be divisible by data shards {data_shards} × "
            f"num_microbatches {num_microbatches}")
    if param_specs is None:
        param_specs = jax.tree.map(
            lambda p: P(axis_name, *([None] * (p.ndim - 1))), stage_params)
    if x_specs is None:
        x_specs = jax.tree.map(
            lambda a: P(batch_axes or None, *([None] * (a.ndim - 1))), xs)
    if schedule == "1f1b":
        return _pipeline_1f1b(
            stage_fn, stage_params, xs, mesh=mesh,
            num_microbatches=num_microbatches, axis_name=axis_name,
            local_batch=local_batch, x_specs=x_specs, param_specs=param_specs)
    if schedule != "gpipe":
        raise ValueError(f"unknown pipeline schedule {schedule!r}")
    mb = local_batch // num_microbatches
    fn = jax.checkpoint(stage_fn) if checkpoint_stages else stage_fn

    def worker(params, xs_local):
        # params leaves: [1, ...] (this stage's slice); xs leaves [local_b,...]
        params = jax.tree.map(lambda p: p[0], params)
        s = jax.lax.axis_index(axis_name)
        m = num_microbatches
        xs_mb = jax.tree.map(
            lambda a: a.reshape(m, mb, *a.shape[1:]), xs_local)
        send_perm = [(i, i + 1) for i in range(n_stages - 1)]

        def tick(carry, t):
            buf, out = carry
            mb_idx = t - s
            active = jnp.logical_and(mb_idx >= 0, mb_idx < m)
            feed = jax.tree.map(lambda a: a[jnp.clip(t, 0, m - 1)], xs_mb)
            x_in = jax.tree.map(
                lambda f, b: jnp.where(s == 0, f, b), feed, buf)
            y = fn(params, x_in)
            y = jax.tree.map(
                lambda a: jnp.where(active, a, jnp.zeros_like(a)), y)
            # Last stage deposits its finished microbatch.
            write = jnp.logical_and(active, s == n_stages - 1)
            idx = jnp.clip(mb_idx, 0, m - 1)
            out = jax.tree.map(
                lambda o, a: jnp.where(
                    write, jax.lax.dynamic_update_index_in_dim(o, a, idx, 0),
                    o),
                out, y)
            # Hop to the next stage (stage n-1 sends to nobody; ppermute
            # without a wrap edge delivers zeros to stage 0, which ignores it)
            buf_next = jax.tree.map(
                lambda a: jax.lax.ppermute(a, axis_name, send_perm), y)
            return (buf_next, out), None

        out0 = jax.tree.map(
            lambda a: jnp.zeros((m, mb, *a.shape[1:]), a.dtype), xs_local)
        buf0 = jax.tree.map(
            lambda a: jnp.zeros((mb, *a.shape[1:]), a.dtype), xs_local)
        (_, out), _ = jax.lax.scan(
            tick, (buf0, out0), jnp.arange(m + n_stages - 1))
        # Replicate the result off the last stage (psum of one-hot owner).
        def collect(o):
            owner = (s == n_stages - 1).astype(o.dtype)
            o = jax.lax.psum(o * owner, axis_name)
            return o.reshape(local_batch, *o.shape[2:])

        return jax.tree.map(collect, out)

    return shard_map(
        worker, mesh=mesh,
        in_specs=(param_specs, x_specs),
        out_specs=x_specs,
        check_vma=False,
    )(stage_params, xs)


def _spec_axes(spec) -> set:
    """Mesh axes a PartitionSpec shards over."""
    axes: set = set()
    for entry in spec:
        if entry is None:
            continue
        axes.update((entry,) if isinstance(entry, str) else entry)
    return axes


def _pipeline_1f1b(stage_fn, stage_params, xs, *, mesh, num_microbatches,
                   axis_name, local_batch, x_specs, param_specs):
    """1F1B: GPipe-style streaming forward + a hand-scheduled interleaved
    backward under ``jax.custom_vjp``.

    Backward super-tick t at stage s (n stages, m microbatches):
      - forward-recompute lane: microbatch ``fi = t - s`` (the GPipe wave);
      - backward lane: microbatch ``bi = t - (2n - 2 - s)`` — the last stage
        backprops a microbatch in the same tick its recompute lands, earlier
        stages 2·(n-1-s) ticks later, exactly the 1F1B pattern.
    Both lanes run every tick (masked when out of range): activations hop
    s→s+1 and cotangents hop s+1→s in the same tick, so no device ever
    waits on a branch. A stage holds at most 2n-1 microbatch inputs
    (fi - bi = 2(n-1-s)), so the ring buffer — not m — bounds memory. Cost:
    3 forwards + 1 backward per microbatch per stage (the fwd lane refills
    the ring AND the vjp's primal re-runs the stage) — one extra forward
    over checkpointed GPipe, the price of the m-independent ring.

    Gradient sync, derived from the specs (the hand-written vjp must do what
    shard_map's transpose would have):
      - ``x_axes`` (stream sharded, params replicated — data/seq axes):
        parameter grads psum over them after the scan.
      - ``vjp_axes`` (params sharded, stream replicated — e.g. ``expert``):
        stage_fn psums its partial outputs over these in the forward, and
        ``jax.vjp`` *inside* the worker transposes that psum to a psum, so
        every cotangent below such a site is inflated by the axis size while
        carrying only the local branch's mixing. The exact fix (inductively:
        psum of local cotangents = axis_size × true cotangent at every
        level): pmean local vjp outputs over these axes — for param leaves
        *sharded* on such an axis, divide by the axis size instead (pmean
        would average different experts' grads)."""
    n = mesh.shape[axis_name]
    m = num_microbatches
    mb = local_batch // m
    ring_depth = 2 * n - 1
    send_perm = [(i, i + 1) for i in range(n - 1)]
    recv_perm = [(i + 1, i) for i in range(n - 1)]

    is_spec = lambda s: isinstance(s, P)
    x_axes: set = set()
    for spec in jax.tree.leaves(x_specs, is_leaf=is_spec):
        x_axes |= _spec_axes(spec)
    p_axes: set = set()
    for spec in jax.tree.leaves(param_specs, is_leaf=is_spec):
        p_axes |= _spec_axes(spec)
    # Axes whose collectives jax.vjp mis-transposes inside the worker (see
    # docstring): params sharded there, the stream not.
    vjp_axes = tuple(a for a in mesh.axis_names
                     if a in p_axes and a != axis_name and a not in x_axes)

    for leaf in jax.tree.leaves(xs):
        if not jnp.issubdtype(leaf.dtype, jnp.inexact):
            raise TypeError(
                "1f1b pipeline streams cotangents; every xs leaf must be "
                f"inexact (got {leaf.dtype}) — close over integer inputs "
                "in stage_fn instead")

    def fwd_worker(params, xs_local):
        params1 = jax.tree.map(lambda p: p[0], params)
        s = jax.lax.axis_index(axis_name)
        xs_mb = jax.tree.map(
            lambda a: a.reshape(m, mb, *a.shape[1:]), xs_local)

        def tick(carry, t):
            buf, out = carry
            fi = t - s
            active = jnp.logical_and(fi >= 0, fi < m)
            feed = jax.tree.map(lambda a: a[jnp.clip(fi, 0, m - 1)], xs_mb)
            x_in = jax.tree.map(
                lambda f, b: jnp.where(s == 0, f, b), feed, buf)
            y = stage_fn(params1, x_in)
            y = jax.tree.map(
                lambda a: jnp.where(active, a, jnp.zeros_like(a)), y)
            write = jnp.logical_and(active, s == n - 1)
            idx = jnp.clip(fi, 0, m - 1)
            out = jax.tree.map(
                lambda o, a: jnp.where(
                    write, jax.lax.dynamic_update_index_in_dim(o, a, idx, 0),
                    o),
                out, y)
            buf_next = jax.tree.map(
                lambda a: jax.lax.ppermute(a, axis_name, send_perm), y)
            return (buf_next, out), None

        out0 = jax.tree.map(
            lambda a: jnp.zeros((m, mb, *a.shape[1:]), a.dtype), xs_local)
        buf0 = jax.tree.map(
            lambda a: jnp.zeros((mb, *a.shape[1:]), a.dtype), xs_local)
        (_, out), _ = jax.lax.scan(tick, (buf0, out0), jnp.arange(m + n - 1))

        def collect(o):
            owner = (s == n - 1).astype(o.dtype)
            o = jax.lax.psum(o * owner, axis_name)
            return o.reshape(local_batch, *o.shape[2:])

        return jax.tree.map(collect, out)

    def bwd_worker(params, xs_local, gys_local):
        params1 = jax.tree.map(lambda p: p[0], params)
        s = jax.lax.axis_index(axis_name)
        xs_mb = jax.tree.map(
            lambda a: a.reshape(m, mb, *a.shape[1:]), xs_local)
        gys_mb = jax.tree.map(
            lambda a: a.reshape(m, mb, *a.shape[1:]), gys_local)

        def tick(carry, t):
            ring, fbuf, gbuf, dparams, dxs = carry
            # -- forward-recompute lane: microbatch fi enters this stage
            fi = t - s
            f_active = jnp.logical_and(fi >= 0, fi < m)
            feed = jax.tree.map(lambda a: a[jnp.clip(fi, 0, m - 1)], xs_mb)
            x_in = jax.tree.map(
                lambda f, b: jnp.where(s == 0, f, b), feed, fbuf)
            fslot = jnp.clip(fi, 0, m - 1) % ring_depth
            ring = jax.tree.map(
                lambda r, x: jnp.where(
                    f_active,
                    jax.lax.dynamic_update_index_in_dim(r, x, fslot, 0), r),
                ring, x_in)
            y = stage_fn(params1, x_in)
            # -- backward lane: microbatch bi leaves this stage
            bi = t - (2 * n - 2 - s)
            b_active = jnp.logical_and(bi >= 0, bi < m)
            bslot = jnp.clip(bi, 0, m - 1) % ring_depth
            x_saved = jax.tree.map(lambda r: r[bslot], ring)
            g_in = jax.tree.map(
                lambda g, b: jnp.where(s == n - 1,
                                       g[jnp.clip(bi, 0, m - 1)], b),
                gys_mb, gbuf)
            _, vjp_fn = jax.vjp(stage_fn, params1, x_saved)
            dp, dx = vjp_fn(g_in)
            if vjp_axes:
                # Restore the exact (replicated) input cotangent before it
                # hops to the previous stage or deposits (docstring: sync).
                dx = jax.tree.map(
                    lambda d: jax.lax.pmean(d, vjp_axes), dx)
            dparams = jax.tree.map(
                lambda acc, d: acc + jnp.where(b_active, d,
                                               jnp.zeros_like(d)),
                dparams, dp)
            deposit = jnp.logical_and(b_active, s == 0)
            dxs = jax.tree.map(
                lambda o, d: jnp.where(
                    deposit,
                    jax.lax.dynamic_update_index_in_dim(
                        o, d, jnp.clip(bi, 0, m - 1), 0),
                    o),
                dxs, dx)
            # -- hops: activations forward, cotangents backward, every tick
            fbuf = jax.tree.map(
                lambda a: jax.lax.ppermute(
                    jnp.where(f_active, a, jnp.zeros_like(a)),
                    axis_name, send_perm), y)
            gbuf = jax.tree.map(
                lambda a: jax.lax.ppermute(
                    jnp.where(b_active, a, jnp.zeros_like(a)),
                    axis_name, recv_perm), dx)
            return (ring, fbuf, gbuf, dparams, dxs), None

        ring0 = jax.tree.map(
            lambda a: jnp.zeros((ring_depth, mb, *a.shape[1:]), a.dtype),
            xs_local)
        fbuf0 = jax.tree.map(
            lambda a: jnp.zeros((mb, *a.shape[1:]), a.dtype), xs_local)
        gbuf0 = jax.tree.map(jnp.zeros_like, fbuf0)
        dparams0 = jax.tree.map(jnp.zeros_like, params1)
        dxs0 = jax.tree.map(
            lambda a: jnp.zeros((m, mb, *a.shape[1:]), a.dtype), xs_local)
        (_, _, _, dparams, dxs), _ = jax.lax.scan(
            tick, (ring0, fbuf0, gbuf0, dparams0, dxs0),
            jnp.arange(m + 2 * n - 2))

        def collect(o):
            owner = (s == 0).astype(o.dtype)
            o = jax.lax.psum(o * owner, axis_name)
            return o.reshape(local_batch, *o.shape[2:])

        def sync_param_grad(d, spec):
            leaf_axes = _spec_axes(spec)
            pmean_axes, scale = [], 1.0
            for a in vjp_axes:
                if a in leaf_axes:
                    scale /= mesh.shape[a]   # sharded leaf: undo inflation
                else:
                    pmean_axes.append(a)     # replicated leaf: exact pmean
            if pmean_axes:
                d = jax.lax.pmean(d, tuple(pmean_axes))
            if scale != 1.0:
                d = d * jnp.asarray(scale, d.dtype)
            # Stream-sharded axes the leaf is replicated over (data/seq):
            # every shard contributes gradient; out_specs claims replication,
            # so the sum happens here (autodiff would have inserted it as
            # the transpose of the implicit broadcast).
            psum_axes = tuple(a for a in mesh.axis_names
                              if a in x_axes and a not in leaf_axes)
            return jax.lax.psum(d, psum_axes) if psum_axes else d

        dparams = jax.tree.map(sync_param_grad, dparams, param_specs)
        return (jax.tree.map(lambda d: d[None], dparams),
                jax.tree.map(collect, dxs))

    fwd_sm = shard_map(fwd_worker, mesh=mesh,
                       in_specs=(param_specs, x_specs),
                       out_specs=x_specs, check_vma=False)
    bwd_sm = shard_map(bwd_worker, mesh=mesh,
                       in_specs=(param_specs, x_specs, x_specs),
                       out_specs=(param_specs, x_specs), check_vma=False)

    @jax.custom_vjp
    def apply(params, xs):
        return fwd_sm(params, xs)

    def apply_fwd(params, xs):
        return fwd_sm(params, xs), (params, xs)

    def apply_bwd(res, gys):
        params, xs_in = res
        dparams, dxs = bwd_sm(params, xs_in, gys)
        return dparams, dxs

    apply.defvjp(apply_fwd, apply_bwd)
    return apply(stage_params, xs)


def sequential_apply(stage_fn: StageFn, stage_params: Any, xs: Any) -> Any:
    """Numerics oracle: same stages, no pipelining."""
    n = jax.tree.leaves(stage_params)[0].shape[0]
    for i in range(n):
        params_i = jax.tree.map(lambda p: p[i], stage_params)
        xs = stage_fn(params_i, xs)
    return xs
