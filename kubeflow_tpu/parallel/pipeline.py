"""Pipeline parallelism: microbatch streaming over the ``pipeline`` mesh axis.

The reference has no pipeline parallelism (SURVEY.md §2.6: 'not implemented'
— user images bring Megatron/DeepSpeed). TPU-natively, stages live on
ICI-neighbor devices and activations hop stage→stage with `lax.ppermute`
inside `shard_map` — the collective-pipelining recipe (cf. the public
scaling-book/praxis pattern), not an NCCL p2p translation.

Schedule: GPipe — m microbatches through n stages in m+n-1 ticks; at tick t
stage s runs microbatch t-s (bubble ticks are masked compute, fraction
(n-1)/(m+n-1)). The whole schedule is a `lax.scan`, so it jits once,
differentiates (ppermute/where/scan all have transposes — reverse-mode
produces the mirrored backward pipeline), and composes with the data axes in
the same mesh (``batch_axes`` shards the batch dim of the streamed pytree).
Stage weights: leading dim sharded over ``pipeline``. Memory: stash
activations per microbatch (GPipe); ``stage_fn`` is wrapped in
``jax.checkpoint`` by default to trade recompute for memory (1F1B's win) —
the schedule itself stays XLA's job.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

StageFn = Callable[[Any, Any], Any]


def stack_stage_params(per_stage: list[Any]) -> Any:
    """[stage0_tree, stage1_tree, ...] → one tree with leading stage dim."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage)


def pipeline_apply(
    stage_fn: StageFn,
    stage_params: Any,                # leaves [n_stages, ...], pipeline-sharded
    xs: Any,                          # pytree; every leaf [batch, ...]
    *,
    mesh: Mesh,
    num_microbatches: int | None = None,
    axis_name: str = "pipeline",
    batch_axes: tuple = ("dcn", "data", "fsdp"),
    checkpoint_stages: bool = True,
) -> Any:
    """Run ``y = stage_{n-1}(... stage_0(xs))`` pipelined over microbatches.

    ``stage_fn(params_one_stage, xs_mb) -> ys_mb`` must preserve the pytree
    structure and leaf shapes (the transformer-stack contract). Every leaf
    streams with the microbatch; the batch dim may additionally be sharded
    over ``batch_axes``. ``num_microbatches=None`` auto-picks the largest
    m ≤ 2·stages dividing the local batch (bubble ≤ ⅓). Returns the same
    pytree, [batch, ...] per leaf."""
    n_stages = mesh.shape[axis_name]
    leaves = jax.tree.leaves(xs)
    batch = leaves[0].shape[0]
    data_shards = 1
    batch_axes = tuple(a for a in batch_axes if a in mesh.axis_names)
    for a in batch_axes:
        data_shards *= mesh.shape[a]
    local_batch = batch // data_shards
    if num_microbatches is None:
        num_microbatches = next(
            (m for m in range(min(2 * n_stages, max(local_batch, 1)), 0, -1)
             if local_batch % m == 0), 1)
    if batch % data_shards or local_batch % num_microbatches:
        raise ValueError(
            f"batch {batch} must be divisible by data shards {data_shards} × "
            f"num_microbatches {num_microbatches}")
    mb = local_batch // num_microbatches
    fn = jax.checkpoint(stage_fn) if checkpoint_stages else stage_fn

    def worker(params, xs_local):
        # params leaves: [1, ...] (this stage's slice); xs leaves [local_b,...]
        params = jax.tree.map(lambda p: p[0], params)
        s = jax.lax.axis_index(axis_name)
        m = num_microbatches
        xs_mb = jax.tree.map(
            lambda a: a.reshape(m, mb, *a.shape[1:]), xs_local)
        send_perm = [(i, i + 1) for i in range(n_stages - 1)]

        def tick(carry, t):
            buf, out = carry
            mb_idx = t - s
            active = jnp.logical_and(mb_idx >= 0, mb_idx < m)
            feed = jax.tree.map(lambda a: a[jnp.clip(t, 0, m - 1)], xs_mb)
            x_in = jax.tree.map(
                lambda f, b: jnp.where(s == 0, f, b), feed, buf)
            y = fn(params, x_in)
            y = jax.tree.map(
                lambda a: jnp.where(active, a, jnp.zeros_like(a)), y)
            # Last stage deposits its finished microbatch.
            write = jnp.logical_and(active, s == n_stages - 1)
            idx = jnp.clip(mb_idx, 0, m - 1)
            out = jax.tree.map(
                lambda o, a: jnp.where(
                    write, jax.lax.dynamic_update_index_in_dim(o, a, idx, 0),
                    o),
                out, y)
            # Hop to the next stage (stage n-1 sends to nobody; ppermute
            # without a wrap edge delivers zeros to stage 0, which ignores it)
            buf_next = jax.tree.map(
                lambda a: jax.lax.ppermute(a, axis_name, send_perm), y)
            return (buf_next, out), None

        out0 = jax.tree.map(
            lambda a: jnp.zeros((m, mb, *a.shape[1:]), a.dtype), xs_local)
        buf0 = jax.tree.map(
            lambda a: jnp.zeros((mb, *a.shape[1:]), a.dtype), xs_local)
        (_, out), _ = jax.lax.scan(
            tick, (buf0, out0), jnp.arange(m + n_stages - 1))
        # Replicate the result off the last stage (psum of one-hot owner).
        def collect(o):
            owner = (s == n_stages - 1).astype(o.dtype)
            o = jax.lax.psum(o * owner, axis_name)
            return o.reshape(local_batch, *o.shape[2:])

        return jax.tree.map(collect, out)

    param_specs = jax.tree.map(
        lambda p: P(axis_name, *([None] * (p.ndim - 1))), stage_params)
    x_specs = jax.tree.map(
        lambda a: P(batch_axes or None, *([None] * (a.ndim - 1))), xs)
    return shard_map(
        worker, mesh=mesh,
        in_specs=(param_specs, x_specs),
        out_specs=x_specs,
        check_vma=False,
    )(stage_params, xs)


def sequential_apply(stage_fn: StageFn, stage_params: Any, xs: Any) -> Any:
    """Numerics oracle: same stages, no pipelining."""
    n = jax.tree.leaves(stage_params)[0].shape[0]
    for i in range(n):
        params_i = jax.tree.map(lambda p: p[i], stage_params)
        xs = stage_fn(params_i, xs)
    return xs
