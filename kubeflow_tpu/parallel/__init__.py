"""Parallelism: logical-axis sharding rules, constraint helpers, collectives.

The reference orchestrates parallelism via env bootstrap and leaves the math
to NCCL inside user containers (SURVEY.md §2.6). Here both halves are owned:
mesh axes come from `runtime.mesh`, and this package maps *logical* tensor
axes (batch/embed/heads/mlp/vocab/expert/...) onto them so models declare
intent once and DP/FSDP/TP/EP/SP all fall out of rule tables.
"""

from kubeflow_tpu.parallel.sharding import (
    DEFAULT_RULES,
    LogicalRules,
    logical_to_mesh_axes,
    named_sharding,
    shard_params,
    with_logical_constraint,
)

__all__ = [
    "DEFAULT_RULES",
    "LogicalRules",
    "logical_to_mesh_axes",
    "named_sharding",
    "shard_params",
    "with_logical_constraint",
]
