"""JAX version compatibility shims.

One module owns the cross-version spelling differences so the data-plane
code reads like current JAX everywhere else:

- ``shard_map``: top-level ``jax.shard_map`` (new), else
  ``jax.experimental.shard_map.shard_map`` with the ``check_vma`` →
  ``check_rep`` keyword translated, else ``None`` (callers and tests gate
  on ``HAS_SHARD_MAP`` — a missing shard_map must degrade to a clean
  skip, not a collection-time ImportError).

The legacy adapter is a standalone factory (``wrap_legacy_shard_map``)
so its keyword translation is directly unit-testable
(tests/test_compat.py) regardless of which jax this environment ships —
the import-time branch below merely selects which implementation feeds
it.
"""

from __future__ import annotations

import functools


def wrap_legacy_shard_map(impl):
    """Adapt ``jax.experimental.shard_map.shard_map`` to the new-style
    calling convention: ``check_vma`` becomes ``check_rep``, and calling
    with only keywords returns a partial (decorator usage)."""

    def shard_map(f=None, /, **kw):
        """``jax.experimental.shard_map`` with new-style keywords."""
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
        if f is None:
            return functools.partial(impl, **kw)
        return impl(f, **kw)

    return shard_map


try:
    from jax import shard_map  # type: ignore[attr-defined]

    HAS_SHARD_MAP = True
    SHARD_MAP_NATIVE = True
except ImportError:  # pragma: no cover - depends on installed jax
    SHARD_MAP_NATIVE = False
    try:
        from jax.experimental.shard_map import shard_map as _shard_map_exp

        shard_map = wrap_legacy_shard_map(_shard_map_exp)
        HAS_SHARD_MAP = True
    except ImportError:
        shard_map = None
        HAS_SHARD_MAP = False


def axis_size(axis_name):
    """``jax.lax.axis_size`` where it exists; the classic static
    ``psum(1, axis)`` idiom (a plain int under shard_map) on older jax."""
    import jax

    try:
        return jax.lax.axis_size(axis_name)
    except AttributeError:
        return jax.lax.psum(1, axis_name)


def require_shard_map():
    """The resolved shard_map, or an ImportError at CALL time (module
    import stays safe for environments without any shard_map)."""
    if shard_map is None:
        raise ImportError(
            "this jax provides neither jax.shard_map nor "
            "jax.experimental.shard_map")
    return shard_map
