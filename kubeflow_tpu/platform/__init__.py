"""Platform surface — REST API server, Prometheus metrics, CLI
(SURVEY.md §2.1 #7 dashboard / L6 gateway analogs, build phase 8): the
HTTP CRUD gateway over the object store, the observability endpoint, and
the ``kftpu``-style command line.
"""

from kubeflow_tpu.platform.api_server import ApiServer

__all__ = ["ApiServer"]
