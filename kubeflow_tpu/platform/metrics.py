"""Prometheus-format platform metrics.

The reference exposes controller-runtime metrics on every controller
(SURVEY.md §5 observability). Here one endpoint aggregates the platform
state the reference surfaces — object/phase counts, event totals — plus the
data-plane numbers it never sees: per-job tokens/sec/chip, step, MFU, and
gang-allocator chip occupancy.

Rendering goes through the unified registry (obs/registry.py): one
Counter/Gauge/Histogram implementation, one label escaper, one exposition
path shared with the model server's and the router's /metrics. ``_line``
and ``render_histogram`` remain as thin compatibility shims over it.
"""

from __future__ import annotations

from typing import Optional

from kubeflow_tpu.core.events import EventRecorder
from kubeflow_tpu.core.jobs import JAXJob, Worker
from kubeflow_tpu.core.registry import known_kinds
from kubeflow_tpu.core.store import ObjectStore
from kubeflow_tpu.obs.registry import MetricsRegistry, format_line


def _line(name: str, value, labels: Optional[dict] = None) -> str:
    """One exposition sample line, with the registry's shared label-value
    escaping (quotes/backslashes/newlines in object names used to emit
    invalid exposition text here)."""
    return format_line(name, value, labels)


def render_histogram(name: str, buckets, counts, total_sum: float,
                     count: int, labels: Optional[dict] = None) -> list[str]:
    """Prometheus histogram lines: cumulative ``_bucket`` series (including
    the ``+Inf`` tail) plus ``_sum``/``_count``. ``counts`` is per-bucket
    (len(buckets) + 1 entries). Compatibility shim over the registry's
    Histogram renderer."""
    reg = MetricsRegistry()
    h = reg.histogram(name, buckets)
    h.set_cumulative(list(counts), total_sum, count, **(labels or {}))
    return h.render()


def build_registry(store: ObjectStore,
                   recorder: Optional[EventRecorder] = None,
                   allocator=None) -> MetricsRegistry:
    """Scrape-time registry over the control plane's object store."""
    reg = MetricsRegistry()

    objects = reg.gauge("kftpu_objects")
    for kind, cls in sorted(known_kinds().items()):
        objs = store.list(cls)
        phases: dict[str, int] = {}
        for o in objs:
            status = getattr(o, "status", None)
            phase = getattr(status, "phase", None) if status is not None else None
            phase = getattr(phase, "value", phase) or "unknown"
            phases[str(phase)] = phases.get(str(phase), 0) + 1
        for phase, n in sorted(phases.items()):
            objects.set(n, kind=kind, phase=phase)

    job_step = reg.gauge("kftpu_job_step")
    for job in store.list(JAXJob):
        m = job.status.metrics
        labels = {"job": job.metadata.name,
                  "namespace": job.metadata.namespace}
        job_step.set(m.step, **labels)
        for field in ("tokens_per_sec_per_chip", "step_time_ms", "mfu", "loss"):
            v = getattr(m, field)
            if v is not None:
                reg.gauge(f"kftpu_job_{field}").set(v, **labels)

    workers = reg.gauge("kftpu_workers")
    worker_phases: dict[str, int] = {}
    for w in store.list(Worker):
        p = getattr(w.status.phase, "value", str(w.status.phase))
        worker_phases[p] = worker_phases.get(p, 0) + 1
    for phase, n in sorted(worker_phases.items()):
        workers.set(n, phase=phase)

    if allocator is not None:
        total, free = allocator.capacity()
        reg.gauge("kftpu_chips_total").set(total)
        reg.gauge("kftpu_chips_allocated").set(total - free)

    if recorder is not None:
        counts: dict[tuple[str, str], int] = {}
        for ev in recorder.all():
            key = (ev.type, ev.reason)
            counts[key] = counts.get(key, 0) + ev.count
        events = reg.counter("kftpu_events_total")
        for (etype, reason), n in sorted(counts.items()):
            events.inc(n, type=etype, reason=reason)

    return reg


def render_metrics(store: ObjectStore,
                   recorder: Optional[EventRecorder] = None,
                   allocator=None) -> str:
    return build_registry(store, recorder, allocator).render()
