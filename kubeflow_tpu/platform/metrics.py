"""Prometheus-format platform metrics.

The reference exposes controller-runtime metrics on every controller
(SURVEY.md §5 observability). Here one endpoint aggregates the platform
state the reference surfaces — object/phase counts, event totals — plus the
data-plane numbers it never sees: per-job tokens/sec/chip, step, MFU, and
gang-allocator chip occupancy.
"""

from __future__ import annotations

from typing import Optional

from kubeflow_tpu.core.events import EventRecorder
from kubeflow_tpu.core.jobs import JAXJob, Worker
from kubeflow_tpu.core.registry import known_kinds
from kubeflow_tpu.core.store import ObjectStore


def _line(name: str, value, labels: Optional[dict] = None) -> str:
    if labels:
        lab = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
        return f"{name}{{{lab}}} {value}"
    return f"{name} {value}"


def render_histogram(name: str, buckets, counts, total_sum: float,
                     count: int, labels: Optional[dict] = None) -> list[str]:
    """Prometheus histogram lines: cumulative ``_bucket`` series (including
    the ``+Inf`` tail) plus ``_sum``/``_count``. ``counts`` is per-bucket
    (len(buckets) + 1 entries); shared by the serving queue-delay histogram
    and any future platform histogram."""
    out = [f"# TYPE {name} histogram"]
    acc = 0
    for le, c in zip(list(buckets) + ["+Inf"], counts):
        acc += c
        out.append(_line(name + "_bucket", acc, {**(labels or {}), "le": le}))
    out.append(_line(name + "_sum", total_sum, labels))
    out.append(_line(name + "_count", count, labels))
    return out


def render_metrics(store: ObjectStore,
                   recorder: Optional[EventRecorder] = None,
                   allocator=None) -> str:
    out: list[str] = []

    out.append("# TYPE kftpu_objects gauge")
    for kind, cls in sorted(known_kinds().items()):
        objs = store.list(cls)
        phases: dict[str, int] = {}
        for o in objs:
            status = getattr(o, "status", None)
            phase = getattr(status, "phase", None) if status is not None else None
            phase = getattr(phase, "value", phase) or "unknown"
            phases[str(phase)] = phases.get(str(phase), 0) + 1
        for phase, n in sorted(phases.items()):
            out.append(_line("kftpu_objects", n,
                             {"kind": kind, "phase": phase}))

    out.append("# TYPE kftpu_job_metric gauge")
    for job in store.list(JAXJob):
        m = job.status.metrics
        labels = {"job": job.metadata.name,
                  "namespace": job.metadata.namespace}
        out.append(_line("kftpu_job_step", m.step, labels))
        for field in ("tokens_per_sec_per_chip", "step_time_ms", "mfu", "loss"):
            v = getattr(m, field)
            if v is not None:
                out.append(_line(f"kftpu_job_{field}", v, labels))

    out.append("# TYPE kftpu_workers gauge")
    worker_phases: dict[str, int] = {}
    for w in store.list(Worker):
        p = getattr(w.status.phase, "value", str(w.status.phase))
        worker_phases[p] = worker_phases.get(p, 0) + 1
    for phase, n in sorted(worker_phases.items()):
        out.append(_line("kftpu_workers", n, {"phase": phase}))

    if allocator is not None:
        total = sum(s.num_chips for s in allocator._cluster.slices)
        free = sum(allocator.free_chips(s.name)
                   for s in allocator._cluster.slices)
        out.append("# TYPE kftpu_chips gauge")
        out.append(_line("kftpu_chips_total", total))
        out.append(_line("kftpu_chips_allocated", total - free))

    if recorder is not None:
        counts: dict[tuple[str, str], int] = {}
        for ev in recorder.all():
            key = (ev.type, ev.reason)
            counts[key] = counts.get(key, 0) + ev.count
        out.append("# TYPE kftpu_events_total counter")
        for (etype, reason), n in sorted(counts.items()):
            out.append(_line("kftpu_events_total", n,
                             {"type": etype, "reason": reason}))

    return "\n".join(out) + "\n"
