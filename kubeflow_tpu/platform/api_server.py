"""REST API gateway over the control plane's object store.

The kube-apiserver-facing L6 surface (SURVEY.md layer map): manifest CRUD,
status, events, worker logs, Prometheus metrics — what the reference spreads
over kubectl + per-app REST backends. stdlib ThreadingHTTPServer, matching
serve/server.py's dependency footprint.

Routes:
- ``GET  /healthz``
- ``GET  /metrics``                         Prometheus text
- ``GET  /apis``                            known kinds
- ``GET  /apis/{kind}?namespace=``          list manifests
- ``GET  /apis/{kind}/{ns}/{name}``         one manifest
- ``POST /apis``                            apply manifest (JSON or YAML body)
- ``DELETE /apis/{kind}/{ns}/{name}``
- ``GET  /events?ref={Kind/ns/name}``
- ``GET  /logs/{ns}/{job}/{replica_index}`` worker log tail
- ``GET/POST/DELETE /volumes/...``          volume browser (pvcviewer +
  volumes-web-app analog; see the volumes section below)
- ``GET  /artifacts[/{name}[/{version}]]``  artifact register read surface
  (artifact:// names → versions → kind/size/cas uri)

Identity: requests may carry ``X-Kftpu-User``; profile-namespace writes are
checked against the Profile's owner/contributors (the KFAM authz surface).
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional
from urllib.parse import parse_qs, unquote, urlparse

import yaml

_SEGMENT_RE = re.compile(r"[A-Za-z0-9][A-Za-z0-9_.-]*")

from kubeflow_tpu.core.headers import USER_HEADER
from kubeflow_tpu.core.manifest import load_manifest
from kubeflow_tpu.core.registry import known_kinds
from kubeflow_tpu.core.store import NotFoundError
from kubeflow_tpu.core.workspace_specs import Profile
from kubeflow_tpu.obs.registry import contract_note_header
from kubeflow_tpu.obs.trace import debug_traces_payload
from kubeflow_tpu.platform.metrics import render_metrics


class ApiServer:
    def __init__(self, control_plane, host: str = "127.0.0.1", port: int = 8134):
        self.cp = control_plane
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):   # quiet
                pass

            def _send(self, code: int, body: Any, content_type="application/json"):
                data = (body if isinstance(body, bytes)
                        else json.dumps(body, default=str).encode()
                        if content_type == "application/json"
                        else str(body).encode())
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                try:
                    outer._get(self)
                except Exception as exc:  # noqa: BLE001 — surface as 500
                    self._send(500, {"error": str(exc)})

            def do_POST(self):
                try:
                    outer._post(self)
                except Exception as exc:  # noqa: BLE001
                    self._send(500, {"error": str(exc)})

            def do_DELETE(self):
                try:
                    outer._delete(self)
                except Exception as exc:  # noqa: BLE001
                    self._send(500, {"error": str(exc)})

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True, name="api-server")
        self._thread.start()

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5.0)

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    # -- authz (KFAM analog) ---------------------------------------------------

    def _authorized(self, handler, namespace: str) -> bool:
        user = handler.headers.get(USER_HEADER)
        contract_note_header(USER_HEADER, direction="read")
        if user is None:
            return True   # no identity → single-user mode
        profile = self.cp.store.try_get(Profile, namespace, "default")
        if profile is None:
            return True   # unmanaged namespace
        return (user == profile.spec.owner
                or user in profile.spec.contributors)

    # -- handlers --------------------------------------------------------------

    def _get(self, h) -> None:
        url = urlparse(h.path)
        parts = [p for p in url.path.split("/") if p]
        q = parse_qs(url.query)
        if url.path == "/healthz":
            return h._send(200, {"ok": True})
        if url.path == "/metrics":
            return h._send(200, render_metrics(
                self.cp.store, self.cp.recorder,
                getattr(self.cp, "allocator", None)), "text/plain")
        if url.path == "/debug/traces":
            # Control-plane trace surface: reconcile spans, pipeline runs,
            # train windows — whatever this process's tracer holds.
            return h._send(200, debug_traces_payload(h.path))
        if url.path == "/apis":
            return h._send(200, {"kinds": sorted(known_kinds())})
        if parts[:1] == ["apis"] and len(parts) == 2:
            cls = self._kind(parts[1])
            if cls is None:
                return h._send(404, {"error": f"unknown kind {parts[1]}"})
            ns = q.get("namespace", [None])[0]
            objs = self.cp.store.list(cls, namespace=ns)
            return h._send(200, {"items": [o.to_manifest() for o in objs]})
        if parts[:1] == ["apis"] and len(parts) == 4:
            cls = self._kind(parts[1])
            if cls is None:
                return h._send(404, {"error": f"unknown kind {parts[1]}"})
            obj = self.cp.store.try_get(cls, parts[3], parts[2])
            if obj is None:
                return h._send(404, {"error": "not found"})
            return h._send(200, obj.to_manifest())
        if parts[:1] == ["events"]:
            ref = q.get("ref", [None])[0]
            evs = (self.cp.recorder.for_object(ref) if ref
                   else self.cp.recorder.all())
            return h._send(200, {"items": [dataclasses.asdict(e) for e in evs]})
        if parts[:1] == ["logs"] and len(parts) == 4:
            return self._logs(h, parts[1], parts[2], parts[3])
        if parts[:1] == ["volumes"]:
            return self._volumes_get(h, [unquote(p) for p in parts[1:]])
        if parts[:1] == ["artifacts"]:
            return self._artifacts_get(h, [unquote(p) for p in parts[1:]])
        if url.path == "/dashboard":
            return self._dashboard(h, q)
        if url.path == "/notebooks/form/config":
            # Spawner form config ((U) jupyter web app spawner_ui_config.yaml
            # — where the reference literally names `nvidia.com/gpu`; here
            # the accelerator is google.com/tpu chips). Images enumerate the
            # kernel-profile registry (the example-notebook-servers family).
            from kubeflow_tpu.core.workspace_specs import KERNEL_PROFILES

            return h._send(200, {
                "images": sorted(KERNEL_PROFILES),
                "image_profiles": {
                    name: {"description": p["description"],
                           "packages": p["packages"]}
                    for name, p in KERNEL_PROFILES.items()},
                "default_image": "jax-notebook",
                "accelerator": {"resource": "google.com/tpu",
                                "counts": [1, 4, 8]},
                "idle_cull_seconds": {"default": 3600, "options":
                                      [600, 1800, 3600, 0]},
            })
        h._send(404, {"error": "no route"})

    # -- artifacts (the register's read surface) -------------------------------

    def _artifacts_get(self, h, parts: list) -> None:
        """GET /artifacts                      registered names
           GET /artifacts/<name>               versions + shape summaries
           GET /artifacts/<name>/<version>     one entry (cas uri, kind,
                                               size) — what an operator
        checks before pointing a storageUri at it."""
        store = self.cp.artifact_store

        def summary(name, version):
            """describe() that degrades per ENTRY: one dangling register
            binding (pruned CAS blob) must not 404 the whole catalog."""
            try:
                return store.describe(store.lookup(name, version))
            except (FileNotFoundError, ValueError) as exc:
                return {"kind": "broken", "error": str(exc)}

        try:
            if not parts:
                # One latest-version summary per name: the listing must not
                # stat every shard of every historical version (O(versions
                # x files)); the per-name route is the full detail view.
                items = {}
                for n in store.names():
                    # Second (tiny) listdir per name — names() already
                    # scanned to filter phantoms; register dirs are small
                    # enough that sharing the scan isn't worth API churn.
                    versions = store.versions(n)
                    items[n] = {
                        "versions": len(versions), "latest": versions[-1],
                        **summary(n, versions[-1])}
                return h._send(200, {"names": list(items), "items": items})
            name = parts[0]
            if len(parts) == 1:
                versions = store.versions(name)
                if not versions:
                    return h._send(404, {"error": f"no artifact {name!r}"})
                return h._send(200, {
                    "name": name,
                    "versions": {v: summary(name, v) for v in versions},
                    "latest": versions[-1]})
            if len(parts) == 2:
                out = store.describe(store.lookup(name, parts[1]))
                out["artifact_uri"] = f"artifact://{name}@{parts[1]}"
                return h._send(200, out)
        except FileNotFoundError as exc:
            return h._send(404, {"error": str(exc)})
        except ValueError as exc:
            return h._send(400, {"error": str(exc)})
        h._send(404, {"error": "no route"})

    # -- dashboard (centraldashboard analog) -----------------------------------

    def _dashboard_data(self) -> dict:
        """One aggregation surface over every namespace: per-kind counts with
        condition rollups, recent events, and links to the other surfaces
        ((U) components/centraldashboard — SURVEY.md §2.1#7; UI stays a
        non-goal, the *capability* is this JSON + the trivial HTML form)."""
        namespaces: dict[str, dict] = {}
        for kind in sorted(known_kinds()):
            cls = self._kind(kind)
            if cls is None:
                continue
            for obj in self.cp.store.list(cls):
                ns = namespaces.setdefault(
                    obj.metadata.namespace, {"kinds": {}})
                row = ns["kinds"].setdefault(
                    kind, {"total": 0, "by_state": {}})
                row["total"] += 1
                state = "—"
                # Some kinds (Pipeline, PodDefault, ServingRuntime) have no
                # status at all — pydantic raises on attribute access, so
                # fetch the status object defensively first.
                status = getattr(obj, "status", None)
                conds = getattr(status, "conditions", None) or []
                # Rollup = the most recently transitioned True condition
                # (the reference surfaces the tail of the ordered list);
                # all-False conditions (e.g. a Failed notebook's
                # Running=False) fall through to the phase.
                live = [c for c in conds if c.status]
                if live:
                    state = max(live,
                                key=lambda c: c.last_transition_time).type
                elif getattr(status, "phase", None) is not None:
                    state = str(getattr(status.phase, "value",
                                        status.phase))
                row["by_state"][state] = row["by_state"].get(state, 0) + 1
        events = [dataclasses.asdict(e) for e in self.cp.recorder.all()[-20:]]
        return {
            "namespaces": namespaces,
            "recent_events": events,
            "links": {
                "kinds": "/apis",
                "objects": "/apis/{kind}?namespace={ns}",
                "events": "/events?ref={Kind/ns/name}",
                "logs": "/logs/{ns}/{job}/{replica_index}",
                "volumes": "/volumes/{ns}",
                "metrics": "/metrics",
            },
        }

    def _dashboard(self, h, q) -> None:
        import html as _html

        data = self._dashboard_data()
        if q.get("format", [None])[0] != "html":
            return h._send(200, data)
        esc = _html.escape   # every interpolated field is user-controlled
        rows = []
        for ns, info in sorted(data["namespaces"].items()):
            for kind, row in sorted(info["kinds"].items()):
                states = ", ".join(f"{esc(s)}: {n}" for s, n
                                   in sorted(row["by_state"].items()))
                rows.append(f"<tr><td>{esc(ns)}</td>"
                            f"<td><a href='/apis/{esc(kind)}?namespace="
                            f"{esc(ns)}'>{esc(kind)}</a></td>"
                            f"<td>{row['total']}</td>"
                            f"<td>{states}</td></tr>")
        evs = "".join(
            f"<li>{esc(e['type'])} {esc(e['object_ref'])} "
            f"{esc(e['reason'])}: {esc(e['message'])}</li>"
            for e in data["recent_events"][-10:])
        html = ("<html><body><h1>kubeflow-tpu dashboard</h1>"
                "<table border=1><tr><th>namespace</th><th>kind</th>"
                "<th>count</th><th>states</th></tr>"
                + "".join(rows) + "</table><h2>recent events</h2><ul>"
                + evs + "</ul>"
                "<p><a href='/metrics'>metrics</a> · "
                "<a href='/apis'>kinds</a> · "
                "<a href='/events'>events</a></p></body></html>")
        h._send(200, html, "text/html")

    def _post(self, h) -> None:
        parts = [p for p in urlparse(h.path).path.split("/") if p]
        if parts == ["artifacts", "gc"]:
            return self._artifacts_gc(h)
        if parts[:1] == ["volumes"] and len(parts) == 3:
            # PVC-create analog: provision an empty volume directory.
            ns, vol = unquote(parts[1]), unquote(parts[2])
            if not self._safe_segment(ns):
                return h._send(400, {"error": "bad namespace"})
            if not self._authorized(h, ns):
                return h._send(403, {"error": "forbidden"})
            root = self._volume_root(ns, vol)
            if root is None:
                return h._send(400, {"error": "bad volume name"})
            os.makedirs(root, exist_ok=True)
            return h._send(200, {"volume": f"{ns}/{vol}"})
        if h.path == "/notebooks/form":
            return self._notebook_form(h)
        if h.path != "/apis":
            return h._send(404, {"error": "no route"})
        length = int(h.headers.get("Content-Length", 0))
        raw = h.rfile.read(length).decode()
        try:
            doc = yaml.safe_load(raw)
            obj = load_manifest(doc)
        except Exception as exc:  # noqa: BLE001 — bad manifest is a 400
            return h._send(400, {"error": f"bad manifest: {exc}"})
        if not self._authorized(h, obj.metadata.namespace):
            return h._send(403, {"error": "forbidden"})
        applied = self.cp.apply(obj)
        h._send(200, applied.to_manifest())

    def _artifacts_gc(self, h) -> None:
        """POST /artifacts/gc {keep_last?, min_age_s?, dry_run?} — platform
        artifact GC (pipelines/gc.py): retention-prune the register, retire
        matching lineage, mark-and-sweep the CAS. Cluster-scoped and
        destructive: in multi-user mode only the admin-namespace
        ("kubeflow" Profile) owner may run it; single-user mode is open
        (matching the rest of the surface)."""
        user = h.headers.get(USER_HEADER)
        if user is not None:
            admin = self.cp.store.try_get(Profile, "kubeflow", "default")
            if admin is None or user != admin.spec.owner:
                return h._send(403, {"error": "artifact gc requires the "
                                              "admin (kubeflow) profile "
                                              "owner"})
        length = int(h.headers.get("Content-Length", 0))
        try:
            body = json.loads(h.rfile.read(length).decode() or "{}")
        except ValueError:
            return h._send(400, {"error": "bad json body"})
        keep_last = body.get("keep_last")
        if keep_last is not None and (isinstance(keep_last, bool)
                                      or not isinstance(keep_last, int)
                                      or keep_last < 1):
            # bool-vs-int matters: JSON true would otherwise read as
            # keep_last=1 and mass-prune every name to one version.
            return h._send(400, {"error": "keep_last must be a positive "
                                          "integer"})
        min_age = body.get("min_age_s", 600.0)
        # NaN would poison the grace-window cutoff (all comparisons False —
        # young blobs sweep, trees never do); strings would 500 in float().
        if not isinstance(min_age, (int, float)) or isinstance(min_age, bool) \
                or min_age != min_age or min_age < 0:
            return h._send(400, {"error": "min_age_s must be a "
                                          "non-negative number"})
        from kubeflow_tpu.pipelines.gc import collect_garbage

        metadata = getattr(
            getattr(self.cp, "pipelinerun_reconciler", None), "metadata",
            None)
        report = collect_garbage(
            self.cp.artifact_store, metadata,
            keep_last=keep_last,
            min_age_s=float(min_age),
            dry_run=bool(body.get("dry_run", False)))
        return h._send(200, report)

    def _delete(self, h) -> None:
        parts = [p for p in urlparse(h.path).path.split("/") if p]
        if parts[:1] == ["volumes"]:
            return self._volumes_delete(h, [unquote(p) for p in parts[1:]])
        if parts[:1] != ["apis"] or len(parts) != 4:
            return h._send(404, {"error": "no route"})
        cls = self._kind(parts[1])
        if cls is None:
            return h._send(404, {"error": f"unknown kind {parts[1]}"})
        if not self._authorized(h, parts[2]):
            return h._send(403, {"error": "forbidden"})
        try:
            self.cp.store.delete(cls, parts[3], parts[2])
        except NotFoundError:
            return h._send(404, {"error": "not found"})
        h._send(200, {"deleted": f"{parts[1]}/{parts[2]}/{parts[3]}"})

    def _notebook_form(self, h) -> None:
        """Spawner form backend ((U) jupyter-web-app
        backend/apps/default/routes/post.py::post_notebook): a flat form
        document becomes a Notebook CR — the form is sugar, the CR is the
        API."""
        from kubeflow_tpu.core.jobs import TPUResourceSpec
        from kubeflow_tpu.core.object import ObjectMeta
        from kubeflow_tpu.core.workspace_specs import Notebook, NotebookSpec

        length = int(h.headers.get("Content-Length", 0))
        try:
            form = json.loads(h.rfile.read(length).decode() or "{}")
            name = form["name"]
        except (ValueError, KeyError, TypeError) as exc:
            return h._send(400, {"error": f"bad form: {exc}"})
        namespace = form.get("namespace", "default")
        if not self._authorized(h, namespace):
            return h._send(403, {"error": "forbidden"})
        # The form's 0 means "never cull" (the Kubeflow convention the
        # config advertises); the spec encodes that as None.
        cull = form.get("idle_cull_seconds", 3600.0)
        if not cull:
            cull = None
        try:
            nb = Notebook(
                metadata=ObjectMeta(name=name, namespace=namespace),
                spec=NotebookSpec(
                    image=form.get("image", "jax-notebook"),
                    resources=TPUResourceSpec(
                        tpu_chips=int(form.get("tpu_chips", 1)),
                        # contract: REST form field — produced by the HTTP client, pinned by TPUResourceSpec.memory_gb
                        memory_gb=form.get("memory_gb")),
                    env={str(k): str(v)
                         for k, v in (form.get("env") or {}).items()},
                    volumes=list(form.get("volumes") or []),
                    idle_cull_seconds=cull,
                    pod_default_labels={
                        str(k): str(v) for k, v in
                        # contract: REST form field — produced by the HTTP client, pinned by NotebookSpec.pod_default_labels
                        (form.get("pod_default_labels") or {}).items()},
                ))
        except Exception as exc:  # noqa: BLE001 — bad form is a 400
            return h._send(400, {"error": f"bad form: {exc}"})
        applied = self.cp.apply(nb)
        h._send(200, applied.to_manifest())

    # -- volumes (pvcviewer + volumes-web-app analog) --------------------------
    #
    # The platform's "volumes" are the per-workload directories under the
    # base dir ((U) kubeflow pvcviewer-controller: filebrowser pod over a
    # PVC; volumes-web-app: PVC CRUD — SURVEY.md §2.1#6/#10). Surface:
    #   GET    /volumes/{ns}                    list volumes + usage
    #   GET    /volumes/{ns}/{vol}              file listing (recursive)
    #   GET    /volumes/{ns}/{vol}/files/<rel>  download raw bytes
    #   POST   /volumes/{ns}/{vol}              provision (PVC create)
    #   DELETE /volumes/{ns}/{vol}              delete the whole volume
    #   DELETE /volumes/{ns}/{vol}/files/<rel>  delete one file
    # All namespace-authorized via the KFAM-analog contributor check.

    @staticmethod
    def _safe_segment(name: str) -> bool:
        """Namespace/volume names: no separators, no dot-names ('.'/'..'
        would remap the path BEFORE the authz check — the namespace string
        that passes authz must be the directory that is touched)."""
        return bool(_SEGMENT_RE.fullmatch(name))

    def _volume_root(self, namespace: str, name: str):
        """Resolve a volume path, refusing traversal outside the base dir."""
        if not (self._safe_segment(namespace) and self._safe_segment(name)):
            return None
        base = os.path.realpath(self.cp.config.base_dir)
        root = os.path.realpath(os.path.join(base, namespace, name))
        if not root.startswith(os.path.join(base, "")) or root == base:
            return None
        return root

    def _volume_file(self, root: str, rel: str):
        full = os.path.realpath(os.path.join(root, rel))
        if full != root and not full.startswith(os.path.join(root, "")):
            return None
        return full

    @staticmethod
    def _stat_or_none(path: str):
        try:
            st = os.stat(path)
        except OSError:
            return None   # deleted mid-walk (checkpoint rotation): skip
        return st

    def _volumes_get(self, h, parts: list) -> None:
        if not parts:
            return h._send(404, {"error": "no route"})
        namespace = parts[0]
        if not self._safe_segment(namespace):
            return h._send(404, {"error": "bad namespace"})
        if not self._authorized(h, namespace):
            return h._send(403, {"error": "forbidden"})
        ns_dir = os.path.join(self.cp.config.base_dir, namespace)
        if len(parts) == 1:
            vols = []
            try:
                names = sorted(os.listdir(ns_dir))
            except OSError:
                names = []
            for name in names:
                root = os.path.join(ns_dir, name)
                if not os.path.isdir(root):
                    continue
                used = 0
                for r, _, files in os.walk(root):
                    for f in files:
                        st = self._stat_or_none(os.path.join(r, f))
                        used += st.st_size if st else 0
                vols.append({"name": name, "used_bytes": used})
            return h._send(200, {"namespace": namespace, "volumes": vols})
        root = self._volume_root(namespace, parts[1])
        if root is None or not os.path.isdir(root):
            return h._send(404, {"error": "no such volume"})
        if len(parts) == 2:
            files = []
            for r, _, names in os.walk(root):
                for n in sorted(names):
                    full = os.path.join(r, n)
                    st = self._stat_or_none(full)
                    if st is None:
                        continue
                    files.append({
                        "path": os.path.relpath(full, root),
                        "bytes": st.st_size,
                        "mtime": st.st_mtime})
            return h._send(200, {"volume": f"{namespace}/{parts[1]}",
                                 "files": files})
        if parts[2] == "files" and len(parts) > 3:
            full = self._volume_file(root, "/".join(parts[3:]))
            if full is None or not os.path.isfile(full):
                return h._send(404, {"error": "no such file"})
            with open(full, "rb") as f:
                return h._send(200, f.read(), "application/octet-stream")
        h._send(404, {"error": "no route"})

    def _volumes_delete(self, h, parts: list) -> None:
        import shutil

        if len(parts) < 2:
            return h._send(404, {"error": "no route"})
        namespace = parts[0]
        if not self._safe_segment(namespace):
            return h._send(404, {"error": "bad namespace"})
        if not self._authorized(h, namespace):
            return h._send(403, {"error": "forbidden"})
        root = self._volume_root(namespace, parts[1])
        if root is None or not os.path.isdir(root):
            return h._send(404, {"error": "no such volume"})
        if len(parts) == 2:
            shutil.rmtree(root)
            return h._send(200, {"deleted": f"{namespace}/{parts[1]}"})
        if parts[2] == "files" and len(parts) > 3:
            full = self._volume_file(root, "/".join(parts[3:]))
            if full is None or not os.path.isfile(full):
                return h._send(404, {"error": "no such file"})
            os.remove(full)
            return h._send(200, {"deleted_file": "/".join(parts[3:])})
        h._send(404, {"error": "no route"})

    def _logs(self, h, namespace: str, job: str, index: str) -> None:
        log = os.path.join(self.cp.config.base_dir, "logs",
                           f"{namespace}.{job}-worker-{index}.log")
        try:
            with open(log, "rb") as f:
                f.seek(0, 2)
                size = f.tell()
                f.seek(max(0, size - 65536))
                data = f.read()
        except OSError:
            return h._send(404, {"error": f"no log at {log}"})
        h._send(200, data, "text/plain")

    @staticmethod
    def _kind(name: str):
        kinds = known_kinds()
        # Accept exact, lowercase, and lowercase-plural forms (kubectl-style).
        for kind, cls in kinds.items():
            if name in (kind, kind.lower(), kind.lower() + "s"):
                return cls
        return None
