"""Train-side input staging — the storage-initializer analog for training
jobs ((U) training-operator sdk `train()`: creates a PVC and an
initContainer that downloads the HF model/dataset before the trainer
starts; SURVEY.md §2.2#22).

``stage_inputs`` resolves dataset/tokenizer URIs into the worker's job dir
before the data pipeline constructs, and can TRAIN a BPE tokenizer from the
staged dataset when asked (the hermetic counterpart of downloading a
pretrained tokenizer). URI schemes: ``file://``, bare paths, and
``artifact://`` — a dataset/tokenizer published into the platform artifact
store (pipelines/artifacts.py), resolved against $KFTPU_ARTIFACT_ROOT the
way serve/storage.py resolves model storageUris. That closes the
pipelines→training seam: ``train(dataset_uri="artifact://corpus@1")``."""

from __future__ import annotations

import logging
import os
import queue
import shutil
import threading
from typing import Any, Callable, Optional

logger = logging.getLogger("kubeflow_tpu.train")


class DeviceBatchStager:
    """Double-buffered host→device input staging for the train loop.

    The K-step scanned dispatch hides the host round-trip *inside* a
    dispatch, but between dispatches the host still synchronously builds
    the next stacked batch (the synthetic source alone walks seq_len numpy
    steps per sample) and uploads it — dead time the device spends idle.
    This stager runs ``fetch(index)`` (build + ``jax.device_put``) on a
    background thread, staying up to ``depth`` batches ahead, so by the
    time dispatch N retires, batch N+1 is already on the device: the
    inter-dispatch host gap goes to the cost of a queue pop.

    ``fetch`` must be a pure function of the index (the data-source
    fast-forward contract), which is what makes prefetching
    restart-transparent. Consumption is strictly sequential from
    ``start`` — ``get`` asserts the index to catch drift. Always
    ``close()`` (or use as a context manager): the thread is daemonic but
    an abandoned stager would keep fetching forever.
    """

    def __init__(self, fetch: Callable[[int], Any], *, start: int = 0,
                 depth: int = 2, name: str = "batch-stager"):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self._fetch = fetch
        self._start = start
        # Queue is the only cross-thread channel (items + errors); the
        # stop event is the only other shared state — both thread-safe
        # primitives, no locking discipline needed.
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=name)
        self._thread.start()

    def _run(self) -> None:
        i = self._start
        while not self._stop.is_set():
            try:
                item = ("ok", i, self._fetch(i))
            except BaseException as exc:
                # Logged here AND forwarded through the queue: get() raises
                # it on the consumer thread, so the loop fails loudly.
                logger.warning("batch staging failed at index %d: %s", i, exc)
                item = ("err", i, exc)
            while not self._stop.is_set():
                try:
                    self._q.put(item, timeout=0.1)
                    break
                except queue.Full:
                    continue
            if item[0] == "err":
                return
            i += 1

    def get(self, index: int, timeout: Optional[float] = None) -> Any:
        """The staged batch for ``index`` (must be consumed in order)."""
        kind, i, payload = self._q.get(timeout=timeout)
        if kind == "err":
            raise RuntimeError(
                f"batch staging failed at index {i}") from payload
        if i != index:
            raise RuntimeError(
                f"batch stager is at index {i} but caller asked for "
                f"{index}; consumption must be sequential from start")
        return payload

    def close(self) -> None:
        self._stop.set()
        # Unblock a put()-blocked producer so the thread exits promptly.
        try:
            self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5.0)

    def __enter__(self) -> "DeviceBatchStager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _resolve(uri: str) -> str:
    if uri.startswith("file://"):
        return uri[len("file://"):]
    if uri.startswith("artifact://") or uri.startswith("cas://"):
        from kubeflow_tpu.pipelines.artifacts import artifact_store_from_env

        store = artifact_store_from_env()
        cas = store.resolve(uri)
        if not store.exists(cas):
            raise FileNotFoundError(f"{uri} ({cas}) is not in the store")
        if store.is_tree(cas):
            # Reject BEFORE localize: materializing a multi-GB checkpoint
            # tree just to refuse it would pay the full copy.
            raise ValueError(
                f"{uri} is a tree artifact; staging consumes file artifacts "
                "(publish the dataset/tokenizer with publish_file)")
        return store.path_for(cas)
    if "://" in uri:
        raise ValueError(f"unsupported staging scheme in {uri!r} "
                         "(file://, artifact:// or a bare path)")
    return uri


def _same_mtime(dst: str, src: str) -> bool:
    """Staged copy carries the source's mtime (copy2). Tolerance of 2s
    covers filesystems that can't preserve timestamps exactly (FAT's 2s
    granularity is the coarsest in practice) — strict equality would
    re-copy the artifact on every start across such mounts, while `>=`
    would treat a source re-materialized with an older preserved timestamp
    as already staged."""
    return abs(os.path.getmtime(dst) - os.path.getmtime(src)) < 2.0


def stage_inputs(
    workdir: str,
    *,
    dataset_uri: Optional[str] = None,
    tokenizer_uri: Optional[str] = None,
    train_tokenizer_vocab: Optional[int] = None,
) -> dict:
    """Copy inputs into ``<workdir>/staged`` and return their local paths:
    {"dataset": path|None, "tokenizer": path|None}. Idempotent (restart
    re-runs it; copies are skipped when sizes match)."""
    staged = os.path.join(workdir, "staged")
    os.makedirs(staged, exist_ok=True)
    out: dict = {"dataset": None, "tokenizer": None}

    if dataset_uri:
        src = _resolve(dataset_uri)
        dst = os.path.join(staged, os.path.basename(src))
        if not (os.path.exists(dst)
                and os.path.getsize(dst) == os.path.getsize(src)
                and _same_mtime(dst, src)):
            shutil.copy2(src, dst)   # refresh when the dataset changed
        out["dataset"] = dst

    if tokenizer_uri:
        src = _resolve(tokenizer_uri)
        dst = os.path.join(staged, os.path.basename(src))
        if not (os.path.exists(dst)
                and os.path.getsize(dst) == os.path.getsize(src)
                and _same_mtime(dst, src)):
            shutil.copy2(src, dst)   # refresh when the artifact changed
        out["tokenizer"] = dst
    elif train_tokenizer_vocab and out["dataset"]:
        from kubeflow_tpu.serve.tokenizer import BPETokenizer

        dst = os.path.join(staged, "tokenizer.bpe.json")
        if not (os.path.exists(dst)
                and os.path.getmtime(dst) >= os.path.getmtime(out["dataset"])):
            # (Re)train when missing or the dataset is newer than the
            # trained artifact.
            with open(out["dataset"], errors="replace") as f:
                tok = BPETokenizer.train(f.read(), train_tokenizer_vocab)
            tok.save(dst + ".tmp")
            os.replace(dst + ".tmp", dst)
        out["tokenizer"] = dst

    return out
