"""Train-side input staging — the storage-initializer analog for training
jobs ((U) training-operator sdk `train()`: creates a PVC and an
initContainer that downloads the HF model/dataset before the trainer
starts; SURVEY.md §2.2#22).

``stage_inputs`` resolves dataset/tokenizer URIs into the worker's job dir
before the data pipeline constructs, and can TRAIN a BPE tokenizer from the
staged dataset when asked (the hermetic counterpart of downloading a
pretrained tokenizer). URI schemes: ``file://``, bare paths, and
``artifact://`` — a dataset/tokenizer published into the platform artifact
store (pipelines/artifacts.py), resolved against $KFTPU_ARTIFACT_ROOT the
way serve/storage.py resolves model storageUris. That closes the
pipelines→training seam: ``train(dataset_uri="artifact://corpus@1")``."""

from __future__ import annotations

import os
import shutil
from typing import Optional


def _resolve(uri: str) -> str:
    if uri.startswith("file://"):
        return uri[len("file://"):]
    if uri.startswith("artifact://") or uri.startswith("cas://"):
        from kubeflow_tpu.pipelines.artifacts import artifact_store_from_env

        store = artifact_store_from_env()
        cas = store.resolve(uri)
        if not store.exists(cas):
            raise FileNotFoundError(f"{uri} ({cas}) is not in the store")
        if store.is_tree(cas):
            # Reject BEFORE localize: materializing a multi-GB checkpoint
            # tree just to refuse it would pay the full copy.
            raise ValueError(
                f"{uri} is a tree artifact; staging consumes file artifacts "
                "(publish the dataset/tokenizer with publish_file)")
        return store.path_for(cas)
    if "://" in uri:
        raise ValueError(f"unsupported staging scheme in {uri!r} "
                         "(file://, artifact:// or a bare path)")
    return uri


def _same_mtime(dst: str, src: str) -> bool:
    """Staged copy carries the source's mtime (copy2). Tolerance of 2s
    covers filesystems that can't preserve timestamps exactly (FAT's 2s
    granularity is the coarsest in practice) — strict equality would
    re-copy the artifact on every start across such mounts, while `>=`
    would treat a source re-materialized with an older preserved timestamp
    as already staged."""
    return abs(os.path.getmtime(dst) - os.path.getmtime(src)) < 2.0


def stage_inputs(
    workdir: str,
    *,
    dataset_uri: Optional[str] = None,
    tokenizer_uri: Optional[str] = None,
    train_tokenizer_vocab: Optional[int] = None,
) -> dict:
    """Copy inputs into ``<workdir>/staged`` and return their local paths:
    {"dataset": path|None, "tokenizer": path|None}. Idempotent (restart
    re-runs it; copies are skipped when sizes match)."""
    staged = os.path.join(workdir, "staged")
    os.makedirs(staged, exist_ok=True)
    out: dict = {"dataset": None, "tokenizer": None}

    if dataset_uri:
        src = _resolve(dataset_uri)
        dst = os.path.join(staged, os.path.basename(src))
        if not (os.path.exists(dst)
                and os.path.getsize(dst) == os.path.getsize(src)
                and _same_mtime(dst, src)):
            shutil.copy2(src, dst)   # refresh when the dataset changed
        out["dataset"] = dst

    if tokenizer_uri:
        src = _resolve(tokenizer_uri)
        dst = os.path.join(staged, os.path.basename(src))
        if not (os.path.exists(dst)
                and os.path.getsize(dst) == os.path.getsize(src)
                and _same_mtime(dst, src)):
            shutil.copy2(src, dst)   # refresh when the artifact changed
        out["tokenizer"] = dst
    elif train_tokenizer_vocab and out["dataset"]:
        from kubeflow_tpu.serve.tokenizer import BPETokenizer

        dst = os.path.join(staged, "tokenizer.bpe.json")
        if not (os.path.exists(dst)
                and os.path.getmtime(dst) >= os.path.getmtime(out["dataset"])):
            # (Re)train when missing or the dataset is newer than the
            # trained artifact.
            with open(out["dataset"], errors="replace") as f:
                tok = BPETokenizer.train(f.read(), train_tokenizer_vocab)
            tok.save(dst + ".tmp")
            os.replace(dst + ".tmp", dst)
        out["tokenizer"] = dst

    return out
