"""Checkpointing via orbax: sharded, multi-process-safe save/restore,
with per-step integrity manifests and verified restore.

First-class in this platform (the reference delegates checkpointing to user
code entirely — SURVEY.md §5). Two tiers cooperate at runtime: the trainer
saves on an interval here, and force-saves to a second *emergency* manager
(``max_to_keep=1``) at the next step boundary after a preemption signal —
see ``Trainer.run``. Restore reshards to the *current* mesh, which is what
makes elastic resize (new topology, same logical state) work.

Integrity contract: after a step commits, a manifest (file list + content
checksums) is written under ``<dir>/manifests/<step>.json``. ``restore``
verifies the manifest before handing state back and raises
``CheckpointCorruptionError`` on any mismatch — a torn or corrupted save can
never silently poison a resume. ``resume_from_tiers`` walks back to the
newest step that verifies AND restores across every tier, quarantining bad
step dirs as it goes, so the worst a corrupt checkpoint costs is the
interval since the previous good one."""

from __future__ import annotations

import hashlib
import json
import logging
import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp

logger = logging.getLogger("kubeflow_tpu.train.checkpoint")

_MANIFEST_DIR = "manifests"
_QUARANTINE_DIR = "quarantine"


class CheckpointCorruptionError(RuntimeError):
    """A checkpoint step failed manifest verification (missing/extra files
    or checksum mismatch) — the bytes on disk are not the bytes saved."""


def _sha256(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                return h.hexdigest()
            h.update(b)


class CheckpointManager:
    def __init__(self, directory: str, max_to_keep: int = 3, *,
                 write_manifests: bool = True):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        # Manifest writing is coordinator-only in a multi-process gang
        # (every process verifies, exactly one writes).
        self.write_manifests = write_manifests
        self._max_to_keep = max_to_keep
        self._mgr = self._open()

    def _open(self):
        return ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=self._max_to_keep, create=True,
                enable_async_checkpointing=True,
            ),
        )

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        """Register an (async) save. Returns orbax's acceptance bool — False
        means the save was REJECTED (e.g. save interval policy); callers
        must not treat a False as durable progress. May raise on storage
        failure; callers on the training hot path wrap this (see
        ``Trainer.save``) so a broken checkpoint store degrades to an alarm
        metric, not a dead job."""
        accepted = self._mgr.save(
            step, args=ocp.args.StandardSave(state), force=force)
        self.flush_manifests()
        return accepted

    def restore(self, abstract_state: Any, step: Optional[int] = None,
                *, verify: bool = True) -> Optional[Any]:
        """Restore latest (or given) step onto the shardings carried by
        ``abstract_state`` (a pytree of jax.ShapeDtypeStruct with .sharding
        set — see make_abstract_state). Returns None when nothing saved.

        Verifies the step's manifest first (when one exists) and raises
        ``CheckpointCorruptionError`` on mismatch, BEFORE any bytes reach
        model state. Because the target shardings describe the *current*
        mesh, a restore after a topology change reshards automatically
        (elastic resize)."""
        target = step if step is not None else self._mgr.latest_step()
        if target is None:
            return None
        if verify:
            self.verify_step(target)
        return self._mgr.restore(
            target, args=ocp.args.StandardRestore(abstract_state))

    def latest_step(self) -> Optional[int]:
        """Newest step the manager KNOWS about — async saves register here
        immediately, before their bytes are durable. See
        ``latest_committed_step`` for the on-disk truth."""
        return self._mgr.latest_step()

    def latest_committed_step(self) -> Optional[int]:
        """Newest step that is FINALIZED ON DISK — async saves register with
        the manager immediately but commit in the background, and a gang
        teardown mid-write leaves nothing restorable. Consumers that gate
        destructive moves on "a checkpoint exists" (the elastic autoscaler)
        must use this, not latest_step()."""
        self.flush_manifests()
        steps = ocp.utils.checkpoint_steps(self.directory)
        return max(steps) if steps else None

    def steps_on_disk(self) -> list[int]:
        """Step dirs present in the directory, committed or not — the
        candidate list the verified-resume walk filters. A torn save's dir
        shows up here (and fails verification); a quarantined one does not."""
        try:
            return sorted(int(d) for d in os.listdir(self.directory)
                          if d.isdigit())
        except OSError:
            return []

    # -- integrity manifests ---------------------------------------------------

    def _manifest_path(self, step: int) -> str:
        return os.path.join(self.directory, _MANIFEST_DIR, f"{step}.json")

    def _step_files(self, step: int) -> dict[str, dict]:
        root = os.path.join(self.directory, str(step))
        out: dict[str, dict] = {}
        for base, _, files in os.walk(root):
            for fn in files:
                p = os.path.join(base, fn)
                rel = os.path.relpath(p, root)
                out[rel] = {"size": os.path.getsize(p), "sha256": _sha256(p)}
        return out

    def flush_manifests(self) -> None:
        """Write manifests for every COMMITTED step that lacks one.

        Called after each save, on commit queries, and at close — an async
        save gets its manifest on the first call after its background commit
        lands. A crash inside the commit-to-manifest window leaves a
        committed-but-unverifiable step; restore treats it as legacy
        (restorable, errors still caught by the resume walk)."""
        if not self.write_manifests:
            return
        for step in ocp.utils.checkpoint_steps(self.directory):
            mpath = self._manifest_path(step)
            if os.path.exists(mpath):
                continue
            files = self._step_files(step)
            os.makedirs(os.path.dirname(mpath), exist_ok=True)
            tmp = f"{mpath}.tmp"
            with open(tmp, "w") as f:
                json.dump({"step": step, "files": files}, f)
            os.replace(tmp, mpath)
        # Drop manifests whose step was garbage-collected (max_to_keep).
        mdir = os.path.join(self.directory, _MANIFEST_DIR)
        if os.path.isdir(mdir):
            live = {str(s) for s in self.steps_on_disk()}
            for fn in os.listdir(mdir):
                if fn.endswith(".json") and fn[:-5] not in live:
                    try:
                        os.remove(os.path.join(mdir, fn))
                    except OSError:
                        pass

    def verify_step(self, step: int) -> bool:
        """Check the step's bytes against its manifest. True = verified,
        False = no manifest to verify against (pre-manifest checkpoint or a
        crash in the commit-to-manifest window — restorable, unverified).
        Raises CheckpointCorruptionError on any mismatch."""
        mpath = self._manifest_path(step)
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except FileNotFoundError:
            return False
        except ValueError as exc:
            raise CheckpointCorruptionError(
                f"step {step}: manifest unreadable: {exc}") from exc
        expect: dict = manifest.get("files", {})
        actual = self._step_files(step)
        if set(expect) != set(actual):
            missing = sorted(set(expect) - set(actual))[:3]
            extra = sorted(set(actual) - set(expect))[:3]
            raise CheckpointCorruptionError(
                f"step {step}: file set mismatch (missing={missing}, "
                f"extra={extra})")
        for rel, meta in expect.items():
            got = actual[rel]
            if (got["size"] != meta["size"]
                    or got["sha256"] != meta["sha256"]):
                raise CheckpointCorruptionError(
                    f"step {step}: checksum mismatch in {rel}")
        return True

    def quarantine_step(self, step: int) -> Optional[str]:
        """Move a bad step dir out of the candidate set (into
        ``quarantine/``, preserved for post-mortem) and reopen the orbax
        manager so its in-memory step list forgets it. Returns the
        quarantine path, or None if another process already moved it."""
        src = os.path.join(self.directory, str(step))
        qdir = os.path.join(self.directory, _QUARANTINE_DIR)
        os.makedirs(qdir, exist_ok=True)
        dst = os.path.join(qdir, str(step))
        i = 0
        while os.path.exists(dst):
            i += 1
            dst = os.path.join(qdir, f"{step}.{i}")
        try:
            os.rename(src, dst)
        except OSError:
            return None     # concurrent quarantine by a gang peer
        mpath = self._manifest_path(step)
        try:
            os.remove(mpath)
        except OSError:
            pass
        logger.warning("quarantined corrupt checkpoint step %d -> %s",
                       step, dst)
        self._mgr.close()
        self._mgr = self._open()
        return dst

    @staticmethod
    def make_abstract_state(init_fn, shardings) -> Any:
        """Abstract (shape/dtype/sharding) mirror of ``init_fn()``'s output."""
        shapes = jax.eval_shape(init_fn)
        return jax.tree.map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
            shapes, shardings)

    def wait(self) -> None:
        self._mgr.wait_until_finished()
        self.flush_manifests()

    def close(self) -> None:
        self._mgr.close()
        self.flush_manifests()


def resume_from_tiers(managers: list[tuple[str, CheckpointManager]],
                      abstract_state: Any, *,
                      quarantine: bool = True):
    """Restore the newest VALID step across checkpoint tiers.

    ``managers`` is ``[(tier_name, manager), ...]`` in preference order for
    equal steps (the trainer passes the emergency tier first: after a
    preemption it holds the newest step; on ties it holds the same bytes).
    Walks candidates newest-first; a step that fails verification OR whose
    restore raises is quarantined (post-mortem preserved) and the walk
    falls back to the next older candidate — a corrupt checkpoint can cost
    at most the interval since the previous good one, never the job.

    Returns ``(state, step, tier_name, fallbacks)`` or None when no tier
    holds a restorable step. ``fallbacks`` counts candidates skipped."""
    candidates: list[tuple[int, int, str, CheckpointManager]] = []
    for order, (tier, mgr) in enumerate(managers):
        for step in mgr.steps_on_disk():
            candidates.append((step, -order, tier, mgr))
    candidates.sort(key=lambda c: (c[0], c[1]), reverse=True)
    fallbacks = 0
    for step, _, tier, mgr in candidates:
        try:
            state = mgr.restore(abstract_state, step=step)
        except Exception as exc:    # corruption OR torn/unreadable save
            fallbacks += 1
            logger.error(
                "restore fallback: step %d (%s tier) invalid: %s",
                step, tier, exc)
            if quarantine:
                mgr.quarantine_step(step)
            continue
        if state is None:
            continue
        return state, step, tier, fallbacks
    return None
