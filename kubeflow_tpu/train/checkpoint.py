"""Checkpointing via orbax: sharded, multi-process-safe save/restore.

First-class in this platform (the reference delegates checkpointing to user
code entirely — SURVEY.md §5): the trainer saves on an interval and on
failure signals; restore reshards to the *current* mesh, which is what makes
elastic resize (new topology, same logical state) work.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp


class CheckpointManager:
    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True, enable_async_checkpointing=True,
            ),
        )

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        return self._mgr.save(
            step, args=ocp.args.StandardSave(state), force=force)

    def restore(self, abstract_state: Any, step: Optional[int] = None) -> Optional[Any]:
        """Restore latest (or given) step onto the shardings carried by
        ``abstract_state`` (a pytree of jax.ShapeDtypeStruct with .sharding
        set — see make_abstract_state). Returns None when nothing saved.

        Because the target shardings describe the *current* mesh, a restore
        after a topology change reshards automatically (elastic resize)."""
        target = step if step is not None else self._mgr.latest_step()
        if target is None:
            return None
        return self._mgr.restore(target, args=ocp.args.StandardRestore(abstract_state))

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def latest_committed_step(self) -> Optional[int]:
        """Newest step that is FINALIZED ON DISK — async saves register with
        the manager immediately but commit in the background, and a gang
        teardown mid-write leaves nothing restorable. Consumers that gate
        destructive moves on "a checkpoint exists" (the elastic autoscaler)
        must use this, not latest_step()."""
        steps = ocp.utils.checkpoint_steps(self.directory)
        return max(steps) if steps else None

    @staticmethod
    def make_abstract_state(init_fn, shardings) -> Any:
        """Abstract (shape/dtype/sharding) mirror of ``init_fn()``'s output."""
        shapes = jax.eval_shape(init_fn)
        return jax.tree.map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
            shapes, shardings)

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
