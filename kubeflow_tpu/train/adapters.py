"""Second-framework training adapters.

The reference runs XGBoost/MXNet/Paddle through per-framework controllers
whose only real job is injecting the cluster spec and watching exit codes
((U) training-operator pkg/controller.v1/{xgboost,mxnet,paddlepaddle};
SURVEY.md §2.2#19). Here a framework adapter is just a registered
entrypoint: it reads the SAME WorkerEnv the operator injects for JAX jobs
(coordinator address, world size, rank — the SetClusterSpec analog), does
framework-native rendezvous, and reports through the same metrics.jsonl
convention the controllers/Katib scrape. No per-framework controller
exists because none is needed — the JAXJob controller is framework-neutral
(gangs, restarts, exit-code policy all apply unchanged).

``torch_train``: PyTorch (CPU) data-parallel training with gloo all-reduce
— the live proof that a non-JAX framework runs as a first-class job.
"""

from __future__ import annotations

import os

from kubeflow_tpu.runtime.entrypoints import WorkerContext, register_entrypoint


@register_entrypoint("torch_train")
def torch_train(ctx: WorkerContext) -> int:
    """Distributed PyTorch regression on synthetic data.

    Config: {"steps": int, "batch": int, "hidden": int, "in_dim": int,
    "lr": float, "log_every": int}. Multi-worker jobs rendezvous with gloo
    at the operator's coordinator address (port+1 — the JAX coordination
    service owns the base port) and all-reduce gradients; the coordinator
    writes metrics.jsonl and a final checkpoint.pt.
    """
    import torch
    import torch.distributed as dist

    cfg = ctx.config
    steps = int(cfg.get("steps", 20))
    batch = int(cfg.get("batch", 32))
    hidden = int(cfg.get("hidden", 32))
    in_dim = int(cfg.get("in_dim", 8))
    lr = float(cfg.get("lr", 1e-2))
    log_every = int(cfg.get("log_every", 1))

    world = ctx.env.num_processes
    rank = ctx.env.process_id
    if world > 1:
        # Rendezvous over the SHARED job directory (workdirs are
        # base/ns/job/worker-i), not a TCP port — the operator only
        # reserves the JAX coordinator's port, so any fixed offset could
        # collide with another job's listener. The store file is keyed by
        # the coordinator port, which is freshly allocated per gang
        # attempt, so a restart never reuses a stale store.
        port = ctx.env.coordinator_address.rsplit(":", 1)[1]
        shared = os.path.dirname(ctx.env.workdir.rstrip(os.sep))
        store_file = os.path.join(shared, f"gloo_{port}")
        dist.init_process_group(
            "gloo", init_method=f"file://{store_file}",
            world_size=world, rank=rank)
    else:
        store_file = None

    torch.manual_seed(0)                      # identical init on all ranks
    model = torch.nn.Sequential(
        torch.nn.Linear(in_dim, hidden), torch.nn.Tanh(),
        torch.nn.Linear(hidden, 1))
    opt = torch.optim.Adam(model.parameters(), lr=lr)
    # Fixed teacher so the loss floor is 0 and descent is observable.
    teacher = torch.nn.Linear(in_dim, 1)
    for p in teacher.parameters():
        p.requires_grad_(False)

    from kubeflow_tpu.train.metrics import MetricsEmitter

    emitter = MetricsEmitter(
        jsonl_path=(os.path.join(ctx.env.workdir, "metrics.jsonl")
                    if ctx.env.workdir and ctx.is_coordinator else None))
    gen = torch.Generator().manual_seed(1234 + rank)   # per-rank data shard
    try:
        for step in range(steps):
            x = torch.randn(batch, in_dim, generator=gen)
            y = teacher(x).detach()
            loss = torch.nn.functional.mse_loss(model(x), y)
            opt.zero_grad()
            loss.backward()
            if world > 1:
                for p in model.parameters():
                    dist.all_reduce(p.grad)
                    p.grad /= world
            opt.step()
            if ctx.is_coordinator and ((step + 1) % log_every == 0
                                       or step + 1 == steps):
                emitter.emit(step, {"loss": float(loss.detach())})
        if ctx.is_coordinator and ctx.env.workdir:
            torch.save(model.state_dict(),
                       os.path.join(ctx.env.workdir, "checkpoint.pt"))
        if world > 1:
            # Success path only: retire the store file so the shared job dir
            # never accumulates stale stores (a recycled coordinator port
            # would otherwise join the old store and hang at rendezvous).
            # The explicit barrier guarantees every peer has finished
            # init_process_group before the file disappears — without it a
            # steps=0 run could unlink while a descheduled rank is still
            # polling the store, and FileStore's O_CREAT reopen would leave
            # that rank waiting on an empty file until timeout. On failure
            # paths the file is left behind; fresh-port keying keeps that
            # correct.
            dist.barrier()
            if ctx.is_coordinator and store_file is not None:
                try:
                    os.unlink(store_file)
                except OSError:
                    pass
    finally:
        emitter.close()
        if world > 1:
            dist.destroy_process_group()
    return 0
