"""Training metrics: throughput, MFU, and the emission contract.

Emission doubles as the Katib-analog stdout metrics-collector source
((U) katib pkg/metricscollector StdOut format: "name=value" lines) and as a
JSONL file the operator scrapes onto JAXJob status (SURVEY.md §5 metrics)."""

from __future__ import annotations

import json
import sys
import time
from typing import Optional, TextIO

from kubeflow_tpu.runtime.topology import GENERATIONS


class Throughput:
    """Steady-state throughput over a sliding window (skips compile step)."""

    def __init__(self, tokens_per_step: float, num_chips: int,
                 flops_per_token: float, generation: str = "v5e"):
        self.tokens_per_step = tokens_per_step
        self.num_chips = num_chips
        self.flops_per_token = flops_per_token
        self.peak_flops = GENERATIONS.get(generation, GENERATIONS["v5e"]).bf16_tflops * 1e12
        self._last: Optional[float] = None
        self._ema_dt: Optional[float] = None

    @property
    def ema_step_time_s(self) -> Optional[float]:
        """Smoothed steady step time (seconds); None before two ticks.
        The goodput ledger prices surviving progress with this."""
        return self._ema_dt

    def tick(self, steps_elapsed: int = 1) -> dict:
        """Update with the wall time since the previous tick, which covered
        ``steps_elapsed`` train steps (callers ticking every log interval must
        pass the interval length or all rates are off by that factor)."""
        now = time.perf_counter()
        out: dict = {}
        if self._last is not None and steps_elapsed > 0:
            dt = (now - self._last) / steps_elapsed
            self._ema_dt = dt if self._ema_dt is None else 0.9 * self._ema_dt + 0.1 * dt
            tps = self.tokens_per_step / self._ema_dt
            out = {
                "step_time_ms": self._ema_dt * 1e3,
                "tokens_per_sec": tps,
                "tokens_per_sec_per_chip": tps / self.num_chips,
                "mfu": (self.flops_per_token * tps) / (self.num_chips * self.peak_flops),
            }
        self._last = now
        return out


class MetricsEmitter:
    """Writes `name=value` lines to stdout (tune collector contract) and
    JSON lines to an optional file (operator scrape)."""

    def __init__(self, jsonl_path: Optional[str] = None, stream: Optional[TextIO] = None):
        self.stream = stream or sys.stdout
        self.jsonl_path = jsonl_path
        self.jsonl = open(jsonl_path, "a") if jsonl_path else None

    def emit(self, step: int, metrics: dict) -> None:
        flat = {k: (float(v) if hasattr(v, "item") or isinstance(v, (int, float)) else v)
                for k, v in metrics.items()}
        parts = " ".join(f"{k}={v:.6g}" if isinstance(v, float) else f"{k}={v}"
                         for k, v in sorted(flat.items()))
        print(f"step={step} {parts}", file=self.stream, flush=True)
        if self.jsonl:
            self.jsonl.write(json.dumps({"step": step, **flat}) + "\n")
            self.jsonl.flush()

    def close(self) -> None:
        if self.jsonl:
            self.jsonl.close()
