"""Optimizer factory: AdamW + warmup-cosine + global-norm clipping.

Config-driven so Experiment (HPO) trials can sweep it via flat dicts.

``fused=True`` swaps the optax chain for :class:`FusedAdamW` — one
elementwise pass per leaf with the clip SCALE folded in. The optax chain
pays two extra full-gradient passes the fusion removes: clip_by_global_norm
materializes a scaled gradient tree (read g + write g'), and the
update/apply_updates seam materializes the update tree (write u + read u) —
~4 × params × 4 B of pure HBM traffic per step on top of Adam's inherent
read-modify-write. The global norm also computes ONCE and is returned (the
train step was recomputing it for metrics)."""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax


@dataclasses.dataclass(frozen=True)
class OptimizerConfig:
    name: str = "adamw"
    learning_rate: float = 3e-4
    warmup_steps: int = 100
    total_steps: int = 10000
    min_lr_ratio: float = 0.1
    b1: float = 0.9
    b2: float = 0.95
    eps: float = 1e-8
    weight_decay: float = 0.1
    clip_norm: Optional[float] = 1.0
    # First-moment dtype: "bfloat16" halves mu's HBM (the standard
    # memory/precision trade — nu stays fp32, its dynamic range matters).
    mu_dtype: Optional[str] = None
    # One-pass update + inline clip scale (adamw only); equivalence-tested
    # against the optax chain, A/B'd on-chip (bench.py headline config).
    fused: bool = False

    @classmethod
    def from_dict(cls, d: dict) -> "OptimizerConfig":
        return cls(**{k: v for k, v in d.items()
                      if k in {f.name for f in dataclasses.fields(cls)}})


def make_schedule(cfg: OptimizerConfig) -> optax.Schedule:
    warmup = optax.linear_schedule(0.0, cfg.learning_rate, max(cfg.warmup_steps, 1))
    decay_steps = max(cfg.total_steps - cfg.warmup_steps, 1)
    cosine = optax.cosine_decay_schedule(
        cfg.learning_rate, decay_steps, alpha=cfg.min_lr_ratio)
    return optax.join_schedules([warmup, cosine], [cfg.warmup_steps])


class FusedAdamW(NamedTuple):
    """AdamW whose whole step — clip scale, moment updates, bias
    correction, weight decay, parameter apply — is ONE elementwise
    expression per leaf, fused by XLA into a single HBM pass over
    (g, mu, nu, p). Not an optax.GradientTransformation on purpose: the
    updates-tree interface is exactly the extra materialization being
    removed. ``apply`` returns (new_params, new_opt_state, grad_norm) so
    the caller logs the norm without a second reduction."""

    cfg: OptimizerConfig
    schedule: Any

    def init(self, params) -> dict:
        mu_dt = jnp.dtype(self.cfg.mu_dtype) if self.cfg.mu_dtype else None
        return {
            "count": jnp.zeros((), jnp.int32),
            "mu": jax.tree.map(
                lambda p: jnp.zeros_like(p, dtype=mu_dt or p.dtype), params),
            # nu is fp32 REGARDLESS of param dtype (its dynamic range
            # matters — module docstring), and apply() returns it fp32:
            # init must agree or the scan-carried state changes dtype
            # after one step (trace error) and the abstract checkpoint
            # target desyncs.
            "nu": jax.tree.map(
                lambda p: jnp.zeros_like(p, dtype=jnp.float32), params),
        }

    def apply(self, grads, opt_state, params):
        c = self.cfg
        count = opt_state["count"] + 1
        lr = self.schedule(opt_state["count"])
        gnorm = optax.global_norm(grads)
        scale = jnp.float32(1.0)
        if c.clip_norm is not None:
            scale = jnp.minimum(1.0, c.clip_norm / jnp.maximum(gnorm, 1e-12))
        bc1 = 1.0 - c.b1 ** count.astype(jnp.float32)
        bc2 = 1.0 - c.b2 ** count.astype(jnp.float32)

        def leaf(p, g, m, v):
            g = g.astype(jnp.float32) * scale          # clip folded in
            m32 = m.astype(jnp.float32) * c.b1 + (1.0 - c.b1) * g
            v32 = v * c.b2 + (1.0 - c.b2) * g * g
            update = (m32 / bc1) / (jnp.sqrt(v32 / bc2) + c.eps) \
                + c.weight_decay * p.astype(jnp.float32)
            new_p = p.astype(jnp.float32) - lr * update
            return (new_p.astype(p.dtype), m32.astype(m.dtype), v32)

        out = jax.tree.map(leaf, params, grads, opt_state["mu"],
                           opt_state["nu"])
        treedef = jax.tree.structure(params)
        flat = jax.tree.leaves(out, is_leaf=lambda x: isinstance(x, tuple))
        new_p = jax.tree.unflatten(treedef, [t[0] for t in flat])
        new_mu = jax.tree.unflatten(treedef, [t[1] for t in flat])
        new_nu = jax.tree.unflatten(treedef, [t[2] for t in flat])
        return (new_p, {"count": count, "mu": new_mu, "nu": new_nu}, gnorm)


def apply_optimizer(optimizer, grads, opt_state, params):
    """One update call for either optimizer kind: returns (new_params,
    new_opt_state, grad_norm). Every train step (LLM, vision) goes through
    here so ``fused=True`` works uniformly instead of per-call-site."""
    if isinstance(optimizer, FusedAdamW):
        return optimizer.apply(grads, opt_state, params)
    updates, new_opt = optimizer.update(grads, opt_state, params)
    return (optax.apply_updates(params, updates), new_opt,
            optax.global_norm(grads))


def make_optimizer(cfg: OptimizerConfig):
    sched = make_schedule(cfg)
    if cfg.fused:
        if cfg.name != "adamw":
            raise ValueError("fused=True supports adamw only")
        return FusedAdamW(cfg, sched)
    if cfg.name == "adamw":
        opt = optax.adamw(sched, b1=cfg.b1, b2=cfg.b2, eps=cfg.eps,
                          weight_decay=cfg.weight_decay,
                          mu_dtype=cfg.mu_dtype)
    elif cfg.name == "adam":
        opt = optax.adam(sched, b1=cfg.b1, b2=cfg.b2, eps=cfg.eps,
                         mu_dtype=cfg.mu_dtype)
    elif cfg.name == "sgd":
        opt = optax.sgd(sched, momentum=0.9)
    else:
        raise ValueError(f"unknown optimizer {cfg.name!r}")
    chain = [opt]
    if cfg.clip_norm is not None:
        chain = [optax.clip_by_global_norm(cfg.clip_norm), opt]
    return optax.chain(*chain)
