"""Optimizer factory: AdamW + warmup-cosine + global-norm clipping.

Config-driven so Experiment (HPO) trials can sweep it via flat dicts."""

from __future__ import annotations

import dataclasses
from typing import Optional

import optax


@dataclasses.dataclass(frozen=True)
class OptimizerConfig:
    name: str = "adamw"
    learning_rate: float = 3e-4
    warmup_steps: int = 100
    total_steps: int = 10000
    min_lr_ratio: float = 0.1
    b1: float = 0.9
    b2: float = 0.95
    eps: float = 1e-8
    weight_decay: float = 0.1
    clip_norm: Optional[float] = 1.0
    # First-moment dtype: "bfloat16" halves mu's HBM (the standard
    # memory/precision trade — nu stays fp32, its dynamic range matters).
    mu_dtype: Optional[str] = None

    @classmethod
    def from_dict(cls, d: dict) -> "OptimizerConfig":
        return cls(**{k: v for k, v in d.items()
                      if k in {f.name for f in dataclasses.fields(cls)}})


def make_schedule(cfg: OptimizerConfig) -> optax.Schedule:
    warmup = optax.linear_schedule(0.0, cfg.learning_rate, max(cfg.warmup_steps, 1))
    decay_steps = max(cfg.total_steps - cfg.warmup_steps, 1)
    cosine = optax.cosine_decay_schedule(
        cfg.learning_rate, decay_steps, alpha=cfg.min_lr_ratio)
    return optax.join_schedules([warmup, cosine], [cfg.warmup_steps])


def make_optimizer(cfg: OptimizerConfig) -> optax.GradientTransformation:
    sched = make_schedule(cfg)
    if cfg.name == "adamw":
        opt = optax.adamw(sched, b1=cfg.b1, b2=cfg.b2, eps=cfg.eps,
                          weight_decay=cfg.weight_decay,
                          mu_dtype=cfg.mu_dtype)
    elif cfg.name == "adam":
        opt = optax.adam(sched, b1=cfg.b1, b2=cfg.b2, eps=cfg.eps,
                         mu_dtype=cfg.mu_dtype)
    elif cfg.name == "sgd":
        opt = optax.sgd(sched, momentum=0.9)
    else:
        raise ValueError(f"unknown optimizer {cfg.name!r}")
    chain = [opt]
    if cfg.clip_norm is not None:
        chain = [optax.clip_by_global_norm(cfg.clip_norm), opt]
    return optax.chain(*chain)
