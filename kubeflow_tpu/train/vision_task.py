"""Sharded train setup for the vision families (ViT classification, CLIP
contrastive) — the vision counterpart of train/step.py's decoder task,
reusing the same optimizer factory, logical sharding rules, and donated
train-state shape. Synthetic deterministic data sources mirror train/data.py
(learnable structure so 'loss decreases' is a real signal).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from kubeflow_tpu.models.vision import (
    CLIPConfig, ViTConfig, clip_loss, clip_param_specs, init_clip_params,
    init_vit_params, vit_loss, vit_param_specs,
)
from kubeflow_tpu.parallel.sharding import (
    DEFAULT_RULES, LogicalRules, logical_to_mesh_axes, shard_params,
)
from kubeflow_tpu.train.optim import (
    OptimizerConfig, apply_optimizer, make_optimizer,
)


@dataclasses.dataclass
class VisionTask:
    cfg: Any
    mesh: Mesh
    state: Any
    state_shardings: Any
    batch_shardings: Any
    step_fn: Callable


def _setup(cfg, init_fn, specs_fn, loss_fn, batch_spec_of, opt_cfg, mesh,
           rules, seed):
    optimizer = make_optimizer(opt_cfg)
    params_shape = jax.eval_shape(lambda: init_fn(jax.random.PRNGKey(0), cfg))
    param_sh = shard_params(params_shape, specs_fn(cfg), mesh, rules)
    opt_shape = jax.eval_shape(optimizer.init, params_shape)
    shape_to_sh = {}
    for p, sh in zip(jax.tree.leaves(params_shape), jax.tree.leaves(param_sh)):
        shape_to_sh.setdefault((p.shape, p.dtype), sh)

    def map_opt(leaf):
        key = (leaf.shape, leaf.dtype)
        if key in shape_to_sh and len(leaf.shape) > 0:
            return shape_to_sh[key]
        return NamedSharding(mesh, PartitionSpec())

    shardings = {
        "params": param_sh,
        "opt_state": jax.tree.map(map_opt, opt_shape),
        "step": NamedSharding(mesh, PartitionSpec()),
    }

    def init_state(key):
        params = init_fn(key, cfg)
        return {"params": params, "opt_state": optimizer.init(params),
                "step": jnp.int32(0)}

    state = jax.jit(init_state, out_shardings=shardings)(
        jax.random.PRNGKey(seed))

    batch_shardings = {
        name: NamedSharding(mesh, logical_to_mesh_axes(spec, rules))
        for name, spec in batch_spec_of(cfg).items()}

    def step_impl(state, batch):
        def lf(params):
            return loss_fn(params, batch, cfg, mesh=mesh, rules=rules)

        (loss, metrics), grads = jax.value_and_grad(lf, has_aux=True)(
            state["params"])
        new_params, new_opt, grad_norm = apply_optimizer(
            optimizer, grads, state["opt_state"], state["params"])
        metrics = dict(metrics)
        metrics["grad_norm"] = grad_norm
        return ({"params": new_params, "opt_state": new_opt,
                 "step": state["step"] + 1}, metrics)

    step_fn = jax.jit(step_impl,
                      in_shardings=(shardings, batch_shardings),
                      out_shardings=(shardings, None),
                      donate_argnums=(0,))
    return VisionTask(cfg=cfg, mesh=mesh, state=state,
                      state_shardings=shardings,
                      batch_shardings=batch_shardings, step_fn=step_fn)


def setup_vit_train(cfg: ViTConfig, opt_cfg: OptimizerConfig, mesh: Mesh, *,
                    rules: LogicalRules = DEFAULT_RULES,
                    seed: int = 0) -> VisionTask:
    def batch_spec(cfg):
        return {"images": ("batch", None, None, None), "labels": ("batch",)}

    return _setup(cfg, init_vit_params, vit_param_specs, vit_loss,
                  batch_spec, opt_cfg, mesh, rules, seed)


def setup_clip_train(cfg: CLIPConfig, opt_cfg: OptimizerConfig, mesh: Mesh, *,
                     rules: LogicalRules = DEFAULT_RULES,
                     seed: int = 0) -> VisionTask:
    def batch_spec(cfg):
        return {"images": ("batch", None, None, None),
                "tokens": ("batch", None)}

    return _setup(cfg, init_clip_params, clip_param_specs, clip_loss,
                  batch_spec, opt_cfg, mesh, rules, seed)


# -- synthetic data --------------------------------------------------------------


def vit_batch(cfg: ViTConfig, batch: int, step: int) -> dict:
    """Class-conditional gaussians: label k tints channel k%C in quadrant
    k%4 — linearly separable enough that a learning ViT's loss drops."""
    rng = np.random.default_rng(step)
    labels = rng.integers(0, max(cfg.num_classes, 2), size=batch)
    imgs = rng.normal(0, 0.3, size=(batch, cfg.image_size, cfg.image_size,
                                    cfg.channels)).astype(np.float32)
    half = cfg.image_size // 2
    for i, y in enumerate(labels):
        qh, qw = (y % 4) // 2, (y % 4) % 2
        imgs[i, qh * half:(qh + 1) * half, qw * half:(qw + 1) * half,
             y % cfg.channels] += 1.5
    return {"images": imgs, "labels": labels.astype(np.int32)}


def clip_batch(cfg: CLIPConfig, batch: int, step: int) -> dict:
    """Paired modality toy: token sequence k co-occurs with image tint k."""
    rng = np.random.default_rng(step)
    concept = rng.integers(0, 16, size=batch)
    icfg = cfg.image
    imgs = rng.normal(0, 0.3, size=(batch, icfg.image_size, icfg.image_size,
                                    icfg.channels)).astype(np.float32)
    for i, k in enumerate(concept):
        imgs[i, :, :, k % icfg.channels] += 0.5 + 0.1 * k
    toks = np.zeros((batch, cfg.text_len), dtype=np.int32)
    toks[:, 0] = 1 + concept          # "word" for the concept
    toks[:, 1] = cfg.text_vocab - 1   # EOT (highest id → argmax pooling)
    return {"images": imgs, "tokens": toks}
