"""Training data plane: sharded train step, optimizer, data, checkpointing,
and the trainer loop that JAXJob workers run.

The hot path (SURVEY.md §3.1 "Rebuild hot path") is ``train_step =
jit(loss→grad→update)`` over the job's mesh, with tokens/sec/chip and MFU
measured around it.
"""

from kubeflow_tpu.train.optim import make_optimizer, OptimizerConfig
from kubeflow_tpu.train.step import TrainTask, setup_train
from kubeflow_tpu.train.trainer import Trainer, TrainerConfig

__all__ = [
    "make_optimizer",
    "OptimizerConfig",
    "TrainTask",
    "setup_train",
    "Trainer",
    "TrainerConfig",
]
