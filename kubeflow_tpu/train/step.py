"""Sharded train step construction.

``setup_train`` builds everything a worker needs from (model cfg, optimizer
cfg, mesh): sharded param/optimizer-state initialization (params materialize
directly in their target sharding — no host round-trip), and a donated,
jit-compiled ``step(state, batch) -> (state, metrics)``.

XLA inserts the cross-device collectives (gradient psum over data axes,
all-gather/reduce-scatter for FSDP params) from the shardings alone —
the GSPMD path that replaces the reference's NCCL allreduce world.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from kubeflow_tpu.models.config import DecoderConfig
from kubeflow_tpu.models.decoder import (
    decoder_loss, decoder_param_specs, init_decoder_params,
)
from kubeflow_tpu.parallel.sharding import (
    DEFAULT_RULES, LogicalRules, logical_to_mesh_axes, shard_params,
)
from kubeflow_tpu.train.optim import (
    OptimizerConfig, apply_optimizer, make_optimizer,
)


@dataclasses.dataclass
class TrainTask:
    """Everything a worker needs to run steps."""

    cfg: DecoderConfig
    mesh: Mesh
    optimizer: optax.GradientTransformation
    state: Any                      # {"params", "opt_state", "step"}
    state_shardings: Any
    batch_sharding: NamedSharding
    step_fn: Callable[[Any, jax.Array], tuple[Any, dict]]
    # K steps per device dispatch: scan over stacked [K, ...] batches,
    # returning the last step's metrics. Host round-trip cost (which can
    # dwarf a step on a tunneled chip) amortizes across K.
    multi_step_fn: Callable[[Any, jax.Array], tuple[Any, dict]] = None
    multi_batch_sharding: NamedSharding = None

    @property
    def params(self):
        return self.state["params"]


def _state_shardings(cfg: DecoderConfig, mesh: Mesh, rules: LogicalRules,
                     optimizer) -> Any:
    param_specs = decoder_param_specs(cfg)
    params_shape = jax.eval_shape(
        lambda: init_decoder_params(jax.random.PRNGKey(0), cfg))
    param_sh = shard_params(params_shape, param_specs, mesh, rules)
    # Optimizer state mirrors param shape (adam mu/nu); derive by eval_shape.
    opt_shape = jax.eval_shape(optimizer.init, params_shape)

    # Walk the opt state: any leaf whose shape matches a param leaf gets that
    # param's sharding; scalars/counters are replicated.
    flat_params, ptree = jax.tree.flatten(params_shape)
    flat_psh = jax.tree.leaves(param_sh)
    shape_to_sh = {}
    for p, sh in zip(flat_params, flat_psh):
        shape_to_sh.setdefault((p.shape, p.dtype), sh)

    def map_opt(leaf):
        key = (leaf.shape, leaf.dtype)
        if key in shape_to_sh and len(leaf.shape) > 0:
            return shape_to_sh[key]
        return NamedSharding(mesh, PartitionSpec())

    opt_sh = jax.tree.map(map_opt, opt_shape)
    return {
        "params": param_sh,
        "opt_state": opt_sh,
        "step": NamedSharding(mesh, PartitionSpec()),
    }


def make_state_init(cfg: DecoderConfig, optimizer, seed: int = 0):
    """The single source of truth for the train-state structure — used both
    for sharded init and as the abstract restore target (keeping the two in
    sync is what makes checkpoints forward-compatible with new fields)."""

    def init_fn(key=None):
        params = init_decoder_params(
            key if key is not None else jax.random.PRNGKey(seed), cfg)
        return {
            "params": params,
            "opt_state": optimizer.init(params),
            "step": jnp.int32(0),
        }

    return init_fn


def setup_train(
    cfg: DecoderConfig,
    opt_cfg: OptimizerConfig,
    mesh: Mesh,
    *,
    rules: LogicalRules = DEFAULT_RULES,
    seed: int = 0,
    attn_impl: str = "xla",
    init_state: bool = True,
) -> TrainTask:
    optimizer = make_optimizer(opt_cfg)
    if dict(mesh.shape).get("pipeline", 1) > 1:
        # Pipeline parallelism stages the layer stack: shard the stacked
        # layer dim over the pipeline axis (parallel/pipeline.py streams
        # microbatches through it).
        from kubeflow_tpu.parallel.sharding import with_rule

        rules = with_rule(rules, "layers", "pipeline")
    shardings = _state_shardings(cfg, mesh, rules, optimizer)
    batch_sharding = NamedSharding(
        mesh, logical_to_mesh_axes(("batch", None), rules))

    init_fn = make_state_init(cfg, optimizer, seed)
    sharded_init = jax.jit(init_fn, out_shardings=shardings)
    state = sharded_init(jax.random.PRNGKey(seed)) if init_state else None

    def step_impl(state, batch):
        def loss_fn(params):
            return decoder_loss(params, batch, cfg, attn_impl=attn_impl,
                                mesh=mesh, rules=rules)

        (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state["params"])
        new_params, new_opt, grad_norm = apply_optimizer(
            optimizer, grads, state["opt_state"], state["params"])
        metrics = dict(metrics)
        metrics["loss"] = loss
        metrics["grad_norm"] = grad_norm
        new_state = {
            "params": new_params,
            "opt_state": new_opt,
            "step": state["step"] + 1,
        }
        return new_state, metrics

    step_fn = jax.jit(
        step_impl,
        in_shardings=(shardings, batch_sharding),
        out_shardings=(shardings, None),
        donate_argnums=(0,),
    )

    def multi_step_impl(state, batches):   # batches [K, B, S+1]
        state, ms = jax.lax.scan(step_impl, state, batches)
        return state, jax.tree.map(lambda x: x[-1], ms)

    multi_batch_sharding = NamedSharding(
        mesh, PartitionSpec(None, *batch_sharding.spec))
    multi_step_fn = jax.jit(
        multi_step_impl,
        in_shardings=(shardings, multi_batch_sharding),
        out_shardings=(shardings, None),
        donate_argnums=(0,),
    )

    return TrainTask(
        cfg=cfg, mesh=mesh, optimizer=optimizer, state=state,
        state_shardings=shardings, batch_sharding=batch_sharding,
        step_fn=step_fn, multi_step_fn=multi_step_fn,
        multi_batch_sharding=multi_batch_sharding,
    )
