"""Training survivability: the goodput ledger and the step-progress watchdog.

At supercluster scale (SNIPPETS.md [3]: 6k-chip v5p pods) a preemption every
few hours is the steady state, not an anomaly, so "did the job finish" stops
being the metric that matters — **goodput** (useful step-time over wall time)
is. This module owns the two pieces the trainer itself cannot be trusted to
improvise mid-incident:

- ``GoodputLedger``: a small JSON file in the job workdir that SURVIVES gang
  restarts (every attempt of a job shares the workdir). It accumulates the
  honest accounting — attempts, steps lost to each restart (last recorded
  progress vs. the step actually resumed), emergency saves, restore
  fallbacks, rejected checkpoint saves — and computes goodput from them.
  The trainer folds ``ledger.metrics()`` into every metrics.jsonl window, so
  the operator scrape lifts the whole ledger onto JAXJob status.

- ``StepWatchdog``: a daemon thread that detects a *wedged* train step — a
  hung collective, a deadlocked input pipeline — within a multiple of the
  observed step time. The platform heartbeat cannot catch this case: the
  heartbeat thread is a daemon that keeps beating while the main thread is
  stuck, so the lease never expires. The watchdog dumps every thread's stack
  (the post-mortem a SIGKILL would destroy) and exits with the retryable
  code, handing the incident to the gang-restart machinery in seconds
  instead of never.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
import traceback
from typing import Callable, Optional

from kubeflow_tpu.runtime.bootstrap import EXIT_RETRYABLE

logger = logging.getLogger("kubeflow_tpu.train.survival")

LEDGER_FILENAME = "goodput.json"


class GoodputLedger:
    """Restart-surviving goodput accounting for one job workdir.

    Single-writer by contract: only the coordinator process (process_id 0)
    holds a ledger, and attempts of a job are sequential, so plain
    read-modify-write is safe. Every mutation persists immediately — the
    next write may never come (that is the point of this file)."""

    _COUNTERS = ("attempts", "steps_lost_total", "emergency_saves",
                 "restore_fallbacks", "checkpoint_save_failures")

    def __init__(self, workdir: str):
        self.path = os.path.join(workdir, LEDGER_FILENAME)
        self.data: dict = {
            "wall_start": None,       # first attempt's start (epoch seconds)
            "last_step": 0,           # newest progress any attempt recorded
            "attempts": 0,
            "steps_lost_total": 0,
            "emergency_saves": 0,
            "restore_fallbacks": 0,
            "checkpoint_save_failures": 0,
        }
        try:
            with open(self.path) as f:
                self.data.update(json.load(f))
        except (OSError, ValueError):
            pass

    def _persist(self) -> None:
        tmp = f"{self.path}.tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(self.data, f)
            os.replace(tmp, self.path)
        except OSError:
            logger.warning("goodput ledger write failed: %s", self.path,
                           exc_info=True)

    # -- lifecycle events ------------------------------------------------------

    def record_resume(self, resume_step: int) -> int:
        """A new attempt started, resuming at ``resume_step``. Returns the
        steps this restart lost (progress the previous attempt recorded but
        the resumed state does not contain — work that must be redone)."""
        if self.data["wall_start"] is None:
            self.data["wall_start"] = time.time()
        lost = max(0, int(self.data["last_step"]) - int(resume_step))
        self.data["attempts"] += 1
        self.data["steps_lost_total"] += lost
        self.data["last_step"] = int(resume_step)
        self._persist()
        return lost

    def record_progress(self, step: int) -> None:
        self.data["last_step"] = max(int(self.data["last_step"]), int(step))
        self._persist()

    def record_emergency_save(self, step: int) -> None:
        self.data["emergency_saves"] += 1
        self.data["last_step"] = max(int(self.data["last_step"]), int(step))
        self._persist()

    def record_fallback(self, n: int = 1) -> None:
        self.data["restore_fallbacks"] += int(n)
        self._persist()

    def record_save_failure(self) -> None:
        self.data["checkpoint_save_failures"] += 1
        self._persist()

    # -- the metric ------------------------------------------------------------

    def goodput(self, step: int, step_time_s: Optional[float],
                now: Optional[float] = None) -> Optional[float]:
        """Useful step-time over wall time, capped at 1.0.

        ``step * step_time_s`` approximates the time the surviving progress
        *should* have cost at the observed steady step time; everything else
        the job spent — compile, restart downtime, redone (lost) steps,
        checkpoint stalls — is the goodput gap. None until a steady step
        time exists."""
        if not step_time_s or self.data["wall_start"] is None:
            return None
        wall = (now if now is not None else time.time()) - self.data["wall_start"]
        if wall <= 0:
            return None
        return min(1.0, (int(step) * float(step_time_s)) / wall)

    def metrics(self, step: int, step_time_s: Optional[float]) -> dict:
        """The ledger as metrics.jsonl fields (scraped onto JAXJob status)."""
        out = {k: int(self.data[k]) for k in self._COUNTERS}
        gp = self.goodput(step, step_time_s)
        if gp is not None:
            out["goodput"] = round(gp, 4)
        return out


def dump_all_stacks(out=None) -> None:
    """Every thread's Python stack to ``out`` (default stderr) — the
    wedge post-mortem, written while the process is still alive to write
    it."""
    out = out or sys.stderr
    names = {t.ident: t.name for t in threading.enumerate()}
    for tid, frame in sys._current_frames().items():
        print(f"--- thread {names.get(tid, '?')} ({tid}) ---",
              file=out, flush=False)
        traceback.print_stack(frame, file=out)
    out.flush()


class StepWatchdog:
    """Detects a wedged train loop from inside the worker.

    Armed when the loop starts, fed a monotonic timestamp per completed
    step. The stall threshold adapts to the *observed* step time
    (``multiplier`` x EMA, floored at ``min_seconds``); before the first
    step completes — compile can legitimately take minutes —
    ``startup_grace_seconds`` applies instead. On a stall it dumps every
    thread's stack and calls ``exit_fn`` (default ``os._exit`` with the
    retryable code, because a wedged main thread by definition cannot run
    cleanup — the gang restart is the cleanup)."""

    def __init__(self, *, multiplier: float = 20.0, min_seconds: float = 60.0,
                 startup_grace_seconds: float = 600.0,
                 poll_seconds: float = 0.25,
                 exit_fn: Optional[Callable[[int], None]] = None,
                 on_stall: Optional[Callable[[float], None]] = None):
        self.multiplier = multiplier
        self.min_seconds = min_seconds
        self.startup_grace_seconds = startup_grace_seconds
        self.poll_seconds = poll_seconds
        self.exit_fn = exit_fn or os._exit
        self.on_stall = on_stall
        # lockfree: single-writer latch; readers only observe False->True
        self.fired = False
        self._ema_dt: Optional[float] = None
        self._last_progress = time.monotonic()
        self._last_step = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._last_progress = time.monotonic()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="step-watchdog")
        self._thread.start()

    def step_completed(self, step: int) -> None:
        now = time.monotonic()
        dt = now - self._last_progress
        self._ema_dt = dt if self._ema_dt is None \
            else 0.8 * self._ema_dt + 0.2 * dt
        self._last_progress = now
        self._last_step = step

    def threshold(self) -> float:
        if self._ema_dt is None:
            return self.startup_grace_seconds
        return max(self.min_seconds, self.multiplier * self._ema_dt)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def _run(self) -> None:
        while not self._stop.wait(self.poll_seconds):
            stalled = time.monotonic() - self._last_progress
            limit = self.threshold()
            if stalled <= limit:
                continue
            self.fired = True
            logger.error(
                "watchdog: no step progress for %.1fs (limit %.1fs, last "
                "step %d) — dumping stacks and exiting retryable",
                stalled, limit, self._last_step)
            try:
                dump_all_stacks()
            except Exception:   # the dump is best-effort; the exit is not
                logger.exception("watchdog stack dump failed")
            if self.on_stall is not None:
                try:
                    self.on_stall(stalled)
                except Exception:
                    logger.exception("watchdog on_stall hook failed")
            self.exit_fn(EXIT_RETRYABLE)
            return
