"""The trainer loop a JAXJob worker runs, plus its config.

Ties together: mesh (from the worker bootstrap), sharded train state, data
sharding per process, step loop, orbax checkpoint/resume with data
fast-forward, and metric emission. This loop IS the reference's "user
container training script" — but owned by the platform, so checkpointing,
metrics, and elasticity are guaranteed rather than hoped for.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import signal
import threading
import time
from typing import Any, Optional

import jax
import numpy as np

from kubeflow_tpu.models.config import DecoderConfig, preset
from kubeflow_tpu.obs.trace import get_tracer
from kubeflow_tpu.runtime.bootstrap import EXIT_PREEMPTED
from kubeflow_tpu.runtime.sanitize import mark_compile_warm, recompile_report
from kubeflow_tpu.train.checkpoint import CheckpointManager, resume_from_tiers
from kubeflow_tpu.train.data import DataConfig, make_data_source
from kubeflow_tpu.train.metrics import MetricsEmitter, Throughput
from kubeflow_tpu.train.optim import OptimizerConfig
from kubeflow_tpu.train.step import setup_train
from kubeflow_tpu.train.survival import GoodputLedger, StepWatchdog

logger = logging.getLogger("kubeflow_tpu.train")


@dataclasses.dataclass
class TrainerConfig:
    model: str = "tiny"                       # preset name
    model_overrides: dict = dataclasses.field(default_factory=dict)
    optimizer: dict = dataclasses.field(default_factory=dict)
    data: dict = dataclasses.field(default_factory=dict)
    steps: int = 100
    log_every: int = 10
    # Input staging (storage-initializer analog, train/staging.py): staged
    # into the worker dir before the data pipeline constructs; a staged
    # dataset flips the data kind to "text" automatically.
    dataset_uri: Optional[str] = None
    tokenizer_uri: Optional[str] = None
    train_tokenizer_vocab: Optional[int] = None
    checkpoint_dir: Optional[str] = None
    checkpoint_every: int = 100
    max_checkpoints: int = 3
    # Survivability (ISSUE 9): a preemption (SIGTERM) force-saves to a fast
    # second tier at the next step boundary, so a graceful preemption loses
    # ZERO completed steps instead of up-to-checkpoint_every of them.
    emergency_checkpointing: bool = True
    emergency_checkpoint_dir: Optional[str] = None   # default: <ckpt>-emergency
    # Step-progress watchdog: a wedged step (hung collective, stuck input
    # pipeline) is detected within max(min_seconds, multiplier x observed
    # step time) and exits retryable — faster AND attributed (stack dump),
    # vs. the heartbeat lease, which a wedged-but-alive worker never misses.
    watchdog_enabled: bool = True
    watchdog_multiplier: float = 20.0
    watchdog_min_seconds: float = 60.0
    watchdog_startup_grace_seconds: float = 600.0
    # Chaos-harness hooks (operator/faults.py drives these through job
    # config): {"wedge_at_step": N, "wedge_once_file": path,
    # "save_fail_steps": [N, ...]}. Inert unless set.
    fault_injection: dict = dataclasses.field(default_factory=dict)
    seed: int = 0
    attn_impl: str = "xla"
    generation: str = "v5e"                   # hardware gen for MFU math
    # jax.profiler window (SURVEY.md §5 tracing): trace steps
    # [profile_start_step, profile_start_step + profile_num_steps) into
    # <workdir>/trace, viewable with tensorboard-plugin-profile.
    profile_start_step: Optional[int] = None
    profile_num_steps: int = 3
    # Debug mode (SURVEY.md §5 race-detection analogs): trap NaNs at the op
    # that produced them instead of surfacing as a corrupted loss later.
    debug_nans: bool = False

    @classmethod
    def from_dict(cls, d: dict) -> "TrainerConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


class Trainer:
    def __init__(self, cfg: TrainerConfig, mesh, *,
                 process_id: int = 0, num_processes: int = 1,
                 metrics_path: Optional[str] = None,
                 workdir: Optional[str] = None):
        self.cfg = cfg
        self.mesh = mesh
        self.process_id = process_id
        self.num_processes = num_processes

        if cfg.debug_nans:
            jax.config.update("jax_debug_nans", True)
        self.model_cfg: DecoderConfig = preset(cfg.model, **cfg.model_overrides)
        opt_cfg = OptimizerConfig.from_dict(
            {"total_steps": cfg.steps, **cfg.optimizer})
        data_overrides = dict(cfg.data)
        if cfg.dataset_uri:
            from kubeflow_tpu.train.staging import stage_inputs

            staged = stage_inputs(
                workdir or cfg.checkpoint_dir or ".",
                dataset_uri=cfg.dataset_uri,
                tokenizer_uri=cfg.tokenizer_uri,
                train_tokenizer_vocab=cfg.train_tokenizer_vocab)
            data_overrides.setdefault("kind", "text")
            data_overrides["path"] = staged["dataset"]
            if staged["tokenizer"]:
                data_overrides["tokenizer_path"] = staged["tokenizer"]
        data_cfg = DataConfig(**{
            "vocab_size": self.model_cfg.vocab_size,
            "seq_len": self.model_cfg.max_seq_len,
            **data_overrides,
        })
        if data_cfg.vocab_size > self.model_cfg.vocab_size:
            raise ValueError("data vocab exceeds model vocab")
        # Elastic shape adaptation: the global batch must divide over BOTH
        # the host shards (loader) and the mesh's batch axes (dcn×data×
        # fsdp sharding of the device batch). An auto-resize can land on a
        # world shape the configured batch doesn't divide (e.g. 8 over 3
        # workers); round UP to the nearest valid multiple — the torchrun-
        # elastic convention of adapting batch to world size, logged so
        # the change is visible in the worker log.
        import math

        dp = 1
        for ax in ("dcn", "data", "fsdp"):
            dp *= int(dict(mesh.shape).get(ax, 1))
        gran = math.lcm(max(num_processes, 1), max(dp, 1))
        if data_cfg.global_batch % gran:
            new_gb = -(-data_cfg.global_batch // gran) * gran
            logger.info(
                "global_batch %d not divisible by lcm(processes=%d, "
                "batch-shards=%d)=%d; adjusted to %d for this world shape",
                data_cfg.global_batch, num_processes, dp, gran, new_gb)
            data_cfg = dataclasses.replace(data_cfg, global_batch=new_gb)
        self.data_cfg = data_cfg
        self.data = make_data_source(data_cfg, shard=process_id,
                                     num_shards=num_processes)

        self.task = setup_train(
            self.model_cfg, opt_cfg, mesh, seed=cfg.seed,
            attn_impl=cfg.attn_impl)

        self.ckpt: Optional[CheckpointManager] = None
        self.ckpt_emergency: Optional[CheckpointManager] = None
        if cfg.checkpoint_dir:
            self.ckpt = CheckpointManager(
                cfg.checkpoint_dir, cfg.max_checkpoints,
                write_manifests=(process_id == 0))
            if cfg.emergency_checkpointing:
                self.ckpt_emergency = CheckpointManager(
                    cfg.emergency_checkpoint_dir
                    or f"{cfg.checkpoint_dir.rstrip(os.sep)}-emergency",
                    max_to_keep=1, write_manifests=(process_id == 0))

        # Goodput ledger: coordinator-owned, lives in the workdir so it
        # survives gang restarts (every attempt shares the workdir).
        ledger_dir = workdir or (os.path.dirname(metrics_path)
                                 if metrics_path else None)
        self.ledger: Optional[GoodputLedger] = (
            GoodputLedger(ledger_dir)
            if ledger_dir and process_id == 0 else None)
        self.save_failures = 0
        self._preempted = threading.Event()

        self.emitter = MetricsEmitter(jsonl_path=metrics_path)
        self.throughput = Throughput(
            tokens_per_step=data_cfg.global_batch * data_cfg.seq_len,
            num_chips=mesh.devices.size,
            flops_per_token=self.model_cfg.flops_per_token(),
            generation=cfg.generation,
        )

    # -- checkpoint/resume -----------------------------------------------------

    def try_resume(self) -> int:
        """Restore the newest VALID checkpoint across tiers; returns the
        resume step.

        The emergency tier is preferred when it holds the newest step (a
        graceful preemption resumes with zero completed steps lost). A
        corrupt or torn step is verified against its manifest, quarantined,
        and the walk falls back to the next older valid step — a bad
        checkpoint can never crash the resume or silently poison the
        numerics, and every skip is surfaced as a ``restore_fallbacks``
        metric."""
        if self.ckpt is None:
            return 0
        tiers: list = []
        if self.ckpt_emergency is not None:
            tiers.append(("emergency", self.ckpt_emergency))
        tiers.append(("interval", self.ckpt))
        resumed = resume_from_tiers(
            tiers, self._abstract_state(),
            quarantine=(self.process_id == 0))
        if resumed is None:
            return 0
        state, _, tier, fallbacks = resumed
        self.task.state = state
        step = int(jax.device_get(state["step"]))
        if fallbacks and self.ledger is not None:
            self.ledger.record_fallback(fallbacks)
        logger.info("resumed from checkpoint at step %d (tier=%s, "
                    "fallbacks=%d)", step, tier, fallbacks)
        return step

    def _abstract_state(self):
        from kubeflow_tpu.train.step import make_state_init

        return CheckpointManager.make_abstract_state(
            make_state_init(self.model_cfg, self.task.optimizer),
            self.task.state_shardings)

    def save(self, step: int, *, force: bool = False,
             manager: Optional[CheckpointManager] = None) -> bool:
        """Save through ``manager`` (default: the interval tier). A rejected
        (False return) or FAILED (raising) save is an alarm — logged and
        counted into ``checkpoint_save_failures`` on metrics.jsonl/job
        status — never a crash: training keeps producing steps while the
        checkpoint store misbehaves, and the alarm is what pages someone."""
        mgr = manager if manager is not None else self.ckpt
        if mgr is None:
            return False
        try:
            if step in set(self.cfg.fault_injection.get("save_fail_steps", ())):
                raise OSError(f"injected checkpoint save failure at step {step}")
            accepted = mgr.save(step, self.task.state, force=force)
            if not accepted:
                logger.error("checkpoint save at step %d rejected by the "
                             "manager", step)
        except Exception:
            logger.exception("checkpoint save at step %d failed", step)
            accepted = False
        if not accepted:
            self.save_failures += 1
            if self.ledger is not None:
                self.ledger.record_save_failure()
        return accepted

    # -- the loop --------------------------------------------------------------

    def make_global_batch(self, local_batch: np.ndarray):
        return jax.make_array_from_process_local_data(
            self.task.batch_sharding, local_batch)

    def run(self, *, on_step=None) -> dict:
        start = self.try_resume()
        if self.ledger is not None:
            lost = self.ledger.record_resume(start)
            if lost:
                logger.warning(
                    "restart lost %d completed step(s): last recorded "
                    "progress outran the resumed checkpoint", lost)
        last_metrics: dict = {}
        last_tick_step = start
        prof = self.cfg.profile_start_step
        tracing = False
        tracer = get_tracer()
        window_start = time.time()
        watchdog: Optional[StepWatchdog] = None
        if self.cfg.watchdog_enabled:
            watchdog = StepWatchdog(
                multiplier=self.cfg.watchdog_multiplier,
                min_seconds=self.cfg.watchdog_min_seconds,
                startup_grace_seconds=self.cfg.watchdog_startup_grace_seconds)
            watchdog.start()
        prev_sigterm = self._install_preemption_handler()
        # Double-buffered host→device staging (train/staging.py): batch
        # N+1 is built and uploaded on a background thread while step N
        # runs, so the device never idles on the host's input work.
        # batch_at is a pure function of the step (the fast-forward
        # contract), which keeps prefetching restart-transparent.
        from kubeflow_tpu.train.staging import DeviceBatchStager

        stager = DeviceBatchStager(
            lambda s: self.make_global_batch(self.data.batch_at(s)),
            start=start, name="train-batch-stager")
        # try/finally so ANY exit from the loop — exception mid-window,
        # preemption SystemExit — still stops an open jax.profiler trace,
        # drains the async checkpoint managers (an in-flight save must not
        # be abandoned torn), and closes the metrics emitter.
        try:
            for step in range(start, self.cfg.steps):
                if prof is not None and self.process_id == 0:
                    # `tracing` guards both ends: a resume that lands inside
                    # or past the window must not stop a trace it never
                    # started.
                    if step == prof:
                        jax.profiler.start_trace(self._trace_dir())
                        tracing = True
                    elif tracing and step >= prof + self.cfg.profile_num_steps:
                        jax.profiler.stop_trace()
                        tracing = False
                batch = stager.get(step)
                self.task.state, metrics = self.task.step_fn(self.task.state, batch)
                if step == start:
                    # Training shapes are fixed: everything compiles on the
                    # first executed step, so under KFTPU_SANITIZE=recompile
                    # any later compile is a dispatch-signature defect — the
                    # runtime half of the F6xx rules. No-op when the
                    # sanitizer is off.
                    mark_compile_warm()
                if watchdog is not None:
                    watchdog.step_completed(step + 1)
                if self._preempted.is_set():
                    self._emergency_exit(step + 1)      # raises SystemExit
                if (step + 1) % self.cfg.log_every == 0 or step + 1 == self.cfg.steps:
                    metrics = {k: float(jax.device_get(v)) for k, v in metrics.items()}
                    metrics.update(self.throughput.tick(step + 1 - last_tick_step))
                    # COMMITTED checkpoints only (async saves that a teardown
                    # would abort must not arm the elastic autoscaler): surfaced
                    # through metrics.jsonl onto job status.
                    if self.ckpt is not None:
                        committed = self.ckpt.latest_committed_step()
                        if committed is not None:
                            metrics["last_checkpoint_step"] = committed
                    # Goodput ledger (train/survival.py): restart/fallback/
                    # emergency accounting riding every window onto job
                    # status; the ledger's cumulative counters supersede the
                    # attempt-local save_failures when present.
                    metrics["checkpoint_save_failures"] = self.save_failures
                    if self.ledger is not None:
                        self.ledger.record_progress(step + 1)
                        metrics.update(self.ledger.metrics(
                            step + 1, self.throughput.ema_step_time_s))
                    # One completed span per logged window (obs/trace.py): the
                    # train loop's slice of the platform trace surface. Spans
                    # are retrospective (explicit start) so the hot loop pays
                    # nothing between log points; ``profiling=True`` marks
                    # windows that overlapped a jax.profiler trace, tying the
                    # span to the on-device timeline it summarizes.
                    sp = tracer.start_span(
                        "train.window", start=window_start,
                        steps=f"{last_tick_step}-{step + 1}")
                    for k in ("loss", "step_time_ms", "tokens_per_sec", "mfu"):
                        if k in metrics:
                            sp.set_attrs(**{k: round(float(metrics[k]), 6)})
                    if tracing:
                        sp.set_attrs(profiling=True)
                    sp.end()
                    window_start = time.time()
                    last_tick_step = step + 1
                    last_metrics = metrics
                    if self.process_id == 0:
                        self.emitter.emit(step + 1, metrics)
                if self.cfg.checkpoint_every and (step + 1) % self.cfg.checkpoint_every == 0:
                    self.save(step + 1)
                self._maybe_injected_wedge(step + 1)
                if on_step is not None:
                    on_step(step + 1, last_metrics)
            if self.ckpt is not None and self.ckpt.latest_step() != self.cfg.steps:
                self.save(self.cfg.steps, force=True)
        finally:
            stager.close()
            if prev_sigterm is not None:
                signal.signal(signal.SIGTERM, prev_sigterm)
            if watchdog is not None:
                watchdog.stop()
            if tracing:
                try:
                    jax.profiler.stop_trace()
                except Exception:
                    logger.exception("stopping profiler trace failed")
            for mgr in (self.ckpt, self.ckpt_emergency):
                if mgr is None:
                    continue
                try:
                    # blocking-ok: drain the async save at run end — durability outranks prompt exit
                    mgr.wait()
                    mgr.close()
                except Exception:
                    logger.exception("checkpoint manager close failed")
            self.emitter.close()
        rep = recompile_report()
        if rep.get("steady_count"):
            # At 6k-chip scale each of these cost minutes of cluster time
            # per occurrence; name the dispatch sites so the fix is a
            # grep, not a bisect.
            logger.error(
                "recompile sanitizer: %d steady-state recompile(s) after "
                "the first step: %s", rep["steady_count"],
                "; ".join(f"{e['fn']} x{e['count']} at {e['site']}"
                          for e in rep["steady"]))
        return last_metrics

    # -- survivability (preemption / wedge / chaos hooks) ----------------------

    def _install_preemption_handler(self):
        """SIGTERM = preemption notice, not an order to die mid-step: set a
        flag, emergency-save at the NEXT step boundary, then exit retryable.
        (worker_main's default handler exits immediately, losing everything
        since the last interval save.) Main-thread only — the signal module
        contract; in-process harnesses (tests driving Trainer directly from
        worker threads) simply keep the host's handler. Returns the previous
        handler for the finally-restore, or None when not installed."""
        if threading.current_thread() is not threading.main_thread():
            return None
        try:
            return signal.signal(signal.SIGTERM,
                                 lambda *_: self._preempted.set())
        except (ValueError, OSError) as exc:
            logger.warning("preemption handler not installed: %s", exc)
            return None

    def _emergency_exit(self, step: int) -> None:
        """A preemption landed: force-save the just-completed step to the
        emergency tier, make it durable, record the ledger, and exit with
        the retryable code so ``JAXJobController._handle_failures``
        gang-restarts and resume finds this exact step — a graceful
        preemption loses ZERO completed steps."""
        mgr = self.ckpt_emergency or self.ckpt
        saved = False
        if mgr is not None:
            saved = self.save(step, force=True, manager=mgr)
            try:
                mgr.wait()          # blocking-ok: durable before we die, or it never was
            except Exception:
                logger.exception("emergency checkpoint wait failed")
                saved = False
        if self.ledger is not None:
            self.ledger.record_progress(step)
            if saved:
                self.ledger.record_emergency_save(step)
        logger.warning(
            "preemption: emergency checkpoint at step %d (%s); exiting "
            "retryable", step, "saved" if saved else "SAVE FAILED")
        raise SystemExit(EXIT_PREEMPTED)

    def _maybe_injected_wedge(self, step: int) -> None:
        """Chaos hook: hang the loop at a configured step (a hung collective,
        as far as any failure detector can tell) — the step-progress
        watchdog is the component under test. ``wedge_once_file`` makes the
        wedge fire on the first attempt only, so the gang restart that
        follows can prove the resume."""
        fi = self.cfg.fault_injection
        if fi.get("wedge_at_step") != step:
            return
        once = fi.get("wedge_once_file")
        if once:
            if os.path.exists(once):
                return
            with open(once, "w") as f:
                f.write(str(step))
        logger.warning("fault injection: wedging at step %d", step)
        while True:
            time.sleep(0.25)

    def _trace_dir(self) -> str:
        import os

        base = (os.path.dirname(self.emitter.jsonl_path)
                if getattr(self.emitter, "jsonl_path", None) else ".")
        return os.path.join(base, "trace")
