"""Training data: deterministic synthetic token streams + a grain seam.

The synthetic source generates structured (learnable) sequences so tests can
assert loss decreases; it is seeded by (seed, step) so a restarted worker
fast-forwards exactly to where it left off — the data-iterator fast-forward
required by elastic restart (SURVEY.md §5 checkpoint/resume).
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class DataConfig:
    kind: str = "synthetic"        # synthetic | grain
    vocab_size: int = 256
    seq_len: int = 128
    global_batch: int = 8
    seed: int = 0
    path: Optional[str] = None     # grain: arrayrecord/parquet path


class SyntheticLM:
    """Markov-ish synthetic LM data: next token = (3*tok + noise) % V.

    Learnable by a tiny model in a few hundred steps, deterministic per
    (seed, step, host_shard)."""

    def __init__(self, cfg: DataConfig, shard: int = 0, num_shards: int = 1):
        if cfg.global_batch % num_shards:
            raise ValueError(f"global_batch {cfg.global_batch} not divisible by "
                             f"num_shards {num_shards}")
        self.cfg = cfg
        self.shard = shard
        self.num_shards = num_shards
        self.local_batch = cfg.global_batch // num_shards

    def batch_at(self, step: int) -> np.ndarray:
        """[local_batch, seq_len+1] int32 tokens for this host at `step`."""
        rng = np.random.default_rng([self.cfg.seed, step, self.shard])
        b, s, v = self.local_batch, self.cfg.seq_len + 1, self.cfg.vocab_size
        toks = np.empty((b, s), np.int32)
        toks[:, 0] = rng.integers(0, v, b)
        noise = (rng.random((b, s)) < 0.05)
        rand = rng.integers(0, v, (b, s))
        for t in range(1, s):
            nxt = (3 * toks[:, t - 1] + 7) % v
            toks[:, t] = np.where(noise[:, t], rand[:, t], nxt)
        return toks

    def iterate(self, start_step: int = 0) -> Iterator[np.ndarray]:
        step = start_step
        while True:
            yield self.batch_at(step)
            step += 1


def make_data_source(cfg: DataConfig, shard: int = 0, num_shards: int = 1):
    if cfg.kind == "synthetic":
        return SyntheticLM(cfg, shard, num_shards)
    if cfg.kind == "grain":
        return _grain_source(cfg, shard, num_shards)
    raise ValueError(f"unknown data kind {cfg.kind!r}")


def _grain_source(cfg: DataConfig, shard: int, num_shards: int):
    """Grain-backed source (google/grain is installed); wraps an on-disk
    token array. Kept behind the same batch_at/iterate interface."""
    import grain.python as grain  # noqa: F401  (availability check)

    class GrainSource:
        def __init__(self):
            arr = np.load(cfg.path, mmap_mode="r")
            self.tokens = arr
            self.local_batch = cfg.global_batch // num_shards
            self.per_epoch = max(1, (len(arr) - 1) // (cfg.seq_len + 1))

        def batch_at(self, step: int) -> np.ndarray:
            rng = np.random.default_rng((cfg.seed, step, shard))
            idx = rng.integers(0, self.per_epoch, self.local_batch)
            s = cfg.seq_len + 1
            return np.stack([self.tokens[i * s:(i + 1) * s] for i in idx]).astype(np.int32)

        def iterate(self, start_step: int = 0):
            step = start_step
            while True:
                yield self.batch_at(step)
                step += 1

    return GrainSource()
