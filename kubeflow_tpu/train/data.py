"""Training data: deterministic synthetic token streams + a grain seam.

The synthetic source generates structured (learnable) sequences so tests can
assert loss decreases; it is seeded by (seed, step) so a restarted worker
fast-forwards exactly to where it left off — the data-iterator fast-forward
required by elastic restart (SURVEY.md §5 checkpoint/resume).
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class DataConfig:
    kind: str = "synthetic"        # synthetic | grain | text
    vocab_size: int = 256
    seq_len: int = 128
    global_batch: int = 8
    seed: int = 0
    path: Optional[str] = None     # grain: token .npy; text: raw text file
    # text kind: tokenizer name from the registry ("byte") or a staged BPE
    # json path (serve/tokenizer.py BPETokenizer artifact).
    tokenizer: str = "byte"
    tokenizer_path: Optional[str] = None


class SyntheticLM:
    """Markov-ish synthetic LM data: next token = (3*tok + noise) % V.

    Learnable by a tiny model in a few hundred steps, deterministic per
    (seed, step, host_shard)."""

    def __init__(self, cfg: DataConfig, shard: int = 0, num_shards: int = 1):
        if cfg.global_batch % num_shards:
            raise ValueError(f"global_batch {cfg.global_batch} not divisible by "
                             f"num_shards {num_shards}")
        self.cfg = cfg
        self.shard = shard
        self.num_shards = num_shards
        self.local_batch = cfg.global_batch // num_shards

    def batch_at(self, step: int) -> np.ndarray:
        """[local_batch, seq_len+1] int32 tokens for this host at `step`."""
        rng = np.random.default_rng([self.cfg.seed, step, self.shard])
        b, s, v = self.local_batch, self.cfg.seq_len + 1, self.cfg.vocab_size
        toks = np.empty((b, s), np.int32)
        toks[:, 0] = rng.integers(0, v, b)
        noise = (rng.random((b, s)) < 0.05)
        rand = rng.integers(0, v, (b, s))
        for t in range(1, s):
            nxt = (3 * toks[:, t - 1] + 7) % v
            toks[:, t] = np.where(noise[:, t], rand[:, t], nxt)
        return toks

    def iterate(self, start_step: int = 0) -> Iterator[np.ndarray]:
        step = start_step
        while True:
            yield self.batch_at(step)
            step += 1


def stacked_batches(source, step0: int, k: int) -> np.ndarray:
    """[K, local_batch, seq_len+1] — the K-step dispatch's host-side batch
    stack (bench.py / scripts/mfu_sweep.py), a pure function of
    ``(source, step0)`` so the double-buffered stager
    (train/staging.py::DeviceBatchStager) can build it ahead of time."""
    return np.stack([source.batch_at(step0 + j) for j in range(k)])


def make_data_source(cfg: DataConfig, shard: int = 0, num_shards: int = 1):
    if cfg.kind == "synthetic":
        return SyntheticLM(cfg, shard, num_shards)
    if cfg.kind == "grain":
        return _grain_source(cfg, shard, num_shards)
    if cfg.kind == "text":
        return TextLM(cfg, shard, num_shards)
    raise ValueError(f"unknown data kind {cfg.kind!r}")


class TextLM:
    """Raw text → tokenizer → packed sequences → grain pipeline → batches.

    The real-data path the reference's ``train()`` stages via its
    storage-initializer ((U) training-operator sdk train(): HF dataset
    download + transformers tokenization; SURVEY.md §2.2#22). Here:

    - the text file is tokenized ONCE (byte tokenizer or a staged BPE
      artifact) and cached next to the source as ``<path>.<tag>.tokens.npy``
      — the staging artifact the trainer mmaps;
    - the token stream is packed into ``seq_len+1`` windows and served
      through a ``grain.MapDataset`` epoch-shuffle: random access by global
      step index means a restarted worker fast-forwards EXACTLY (the
      data-iterator contract elastic restart needs) — no iterator state to
      persist, the step number is the state.
    """

    def __init__(self, cfg: DataConfig, shard: int = 0, num_shards: int = 1):
        if cfg.global_batch % num_shards:
            raise ValueError(f"global_batch {cfg.global_batch} not divisible "
                             f"by num_shards {num_shards}")
        if not cfg.path:
            raise ValueError("text data source needs DataConfig.path")
        self.cfg = cfg
        self.shard = shard
        self.num_shards = num_shards
        self.local_batch = cfg.global_batch // num_shards
        self.tokens = self._tokenize_cached()
        if int(self.tokens.max(initial=0)) >= cfg.vocab_size:
            # Checked on EVERY load (a cached tokenization from a previous
            # larger-vocab run must not silently feed out-of-range ids).
            raise ValueError(
                f"tokenized data has ids up to {int(self.tokens.max())} but "
                f"the data config vocab is {cfg.vocab_size}")
        s = cfg.seq_len + 1
        if len(self.tokens) < s:
            raise ValueError(
                f"text at {cfg.path} tokenizes to {len(self.tokens)} tokens "
                f"— need at least seq_len+1 = {s} for one window")
        self.per_epoch = (len(self.tokens) - 1) // s or 1
        import grain.python as grain

        # window index -> packed [seq_len+1] slice; shuffle reshuffles every
        # epoch (grain's index semantics), repeat makes any step addressable.
        self._ds = (
            grain.MapDataset.source(list(range(self.per_epoch)))
            .shuffle(seed=cfg.seed)
            .repeat()
        )

    def _tokenize_cached(self) -> np.ndarray:
        import hashlib
        import os
        import uuid

        from kubeflow_tpu.serve.tokenizer import BPETokenizer, get_tokenizer

        if self.cfg.tokenizer_path:
            tok = BPETokenizer.load(self.cfg.tokenizer_path)
            tag = "bpe-" + hashlib.sha256(
                open(self.cfg.tokenizer_path, "rb").read()).hexdigest()[:8]
        else:
            tok = get_tokenizer(self.cfg.tokenizer)
            tag = self.cfg.tokenizer
        cache = f"{self.cfg.path}.{tag}.tokens.npy"
        if os.path.exists(cache) and (os.path.getmtime(cache)
                                      >= os.path.getmtime(self.cfg.path)):
            return np.load(cache, mmap_mode="r")
        with open(self.cfg.path, errors="replace") as f:
            ids = tok.encode(f.read())
        arr = np.asarray(ids, np.int32)
        if arr.max(initial=0) >= self.cfg.vocab_size:
            raise ValueError(
                f"tokenizer vocab {int(arr.max()) + 1} exceeds data config "
                f"vocab {self.cfg.vocab_size}")
        # Unique per writer (pid alone collides across containers where
        # every main process is PID 1): concurrent stagers must not
        # interleave into one tmp file before the atomic replace. Unlinked
        # on failure — unique names don't self-overwrite on retry, so a
        # crash loop would otherwise accrete full-size orphans.
        tmp = f"{cache}.{os.getpid()}.{uuid.uuid4().hex[:8]}.tmp"
        try:
            with open(tmp, "wb") as f:
                np.save(f, arr)
            os.replace(tmp, cache)   # atomic publish: racing workers see either
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return np.load(cache, mmap_mode="r")

    def batch_at(self, step: int) -> np.ndarray:
        """[local_batch, seq_len+1] for this shard at global ``step`` —
        pure function of (config, step, shard): the fast-forward contract."""
        s = self.cfg.seq_len + 1
        out = np.empty((self.local_batch, s), np.int32)
        base = (step * self.cfg.global_batch
                + self.shard * self.local_batch)
        for j in range(self.local_batch):
            w = self._ds[base + j]
            out[j] = self.tokens[w * s:(w + 1) * s]
        return out

    def iterate(self, start_step: int = 0) -> Iterator[np.ndarray]:
        step = start_step
        while True:
            yield self.batch_at(step)
            step += 1


def _grain_source(cfg: DataConfig, shard: int, num_shards: int):
    """Grain-backed source (google/grain is installed); wraps an on-disk
    token array. Kept behind the same batch_at/iterate interface."""
    import grain.python as grain  # noqa: F401  (availability check)

    class GrainSource:
        def __init__(self):
            arr = np.load(cfg.path, mmap_mode="r")
            self.tokens = arr
            self.local_batch = cfg.global_batch // num_shards
            self.per_epoch = max(1, (len(arr) - 1) // (cfg.seq_len + 1))

        def batch_at(self, step: int) -> np.ndarray:
            rng = np.random.default_rng((cfg.seed, step, shard))
            idx = rng.integers(0, self.per_epoch, self.local_batch)
            s = cfg.seq_len + 1
            return np.stack([self.tokens[i * s:(i + 1) * s] for i in idx]).astype(np.int32)

        def iterate(self, start_step: int = 0):
            step = start_step
            while True:
                yield self.batch_at(step)
                step += 1

    return GrainSource()
