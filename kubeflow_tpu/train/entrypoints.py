"""Registered trainer entrypoints — what JAXJob WorkloadSpecs name.

``llm_pretrain`` is the flagship (BASELINE config 1: Llama-class SPMD
pretraining). Workers receive the mesh from the runtime bootstrap; config
comes from WorkloadSpec.config verbatim (TrainerConfig fields).
"""

from __future__ import annotations

import os

from kubeflow_tpu.runtime.entrypoints import WorkerContext, register_entrypoint


@register_entrypoint("llm_pretrain")
def llm_pretrain(ctx: WorkerContext) -> int:
    import jax

    from kubeflow_tpu.train.trainer import Trainer, TrainerConfig

    cfg = TrainerConfig.from_dict(ctx.config)
    mesh = ctx.mesh
    if mesh is None:
        from kubeflow_tpu.runtime.mesh import build_mesh

        mesh = build_mesh({"fsdp": jax.device_count()})
    metrics_path = None
    if ctx.env.workdir:
        metrics_path = os.path.join(ctx.env.workdir, "metrics.jsonl")
    trainer = Trainer(
        cfg, mesh,
        process_id=ctx.env.process_id,
        num_processes=ctx.env.num_processes,
        metrics_path=metrics_path,
    )
    trainer.run()
    return 0
