"""Registered trainer entrypoints — what JAXJob WorkloadSpecs name.

``llm_pretrain`` is the flagship (BASELINE config 1: Llama-class SPMD
pretraining). Workers receive the mesh from the runtime bootstrap; config
comes from WorkloadSpec.config verbatim (TrainerConfig fields).
"""

from __future__ import annotations

import os

from kubeflow_tpu.runtime.entrypoints import WorkerContext, register_entrypoint


@register_entrypoint("vision_train")
def vision_train(ctx: WorkerContext) -> int:
    """ViT classification / CLIP contrastive training (BASELINE config 4:
    'ViT-L / CLIP via pipelines'). Config: {"family": "vit"|"clip",
    "model": preset, "steps", "batch", "optimizer": {...}}."""
    import jax

    from kubeflow_tpu.models.vision import clip_preset, vit_preset
    from kubeflow_tpu.train.optim import OptimizerConfig
    from kubeflow_tpu.train.vision_task import (
        clip_batch, setup_clip_train, setup_vit_train, vit_batch,
    )

    cfg = ctx.config
    family = cfg.get("family", "vit")
    steps = int(cfg.get("steps", 20))
    batch = int(cfg.get("batch", 8))
    opt = OptimizerConfig.from_dict(
        {"total_steps": steps, "warmup_steps": 0, **cfg.get("optimizer", {})})
    mesh = ctx.mesh
    if mesh is None:
        from kubeflow_tpu.runtime.bootstrap import single_worker_mesh

        mesh = single_worker_mesh(ctx.env, axis="data")
    overrides = dict(cfg.get("model_overrides", {}))
    if family == "vit":
        mcfg = vit_preset(cfg.get("model", "tiny-vit"), **overrides)
        task = setup_vit_train(mcfg, opt, mesh)
        batch_fn = lambda step: vit_batch(mcfg, batch, step)  # noqa: E731
    elif family == "clip":
        mcfg = clip_preset(cfg.get("model", "tiny-clip"), **overrides)
        task = setup_clip_train(mcfg, opt, mesh)
        batch_fn = lambda step: clip_batch(mcfg, batch, step)  # noqa: E731
    else:
        raise ValueError(f"unknown vision family {family!r}")

    from kubeflow_tpu.train.metrics import MetricsEmitter

    emitter = MetricsEmitter(
        jsonl_path=(os.path.join(ctx.env.workdir, "metrics.jsonl")
                    if ctx.env.workdir and ctx.is_coordinator else None))
    log_every = int(cfg.get("log_every", 1))
    state = task.state
    for step in range(steps):
        b = jax.device_put(batch_fn(step), task.batch_shardings)
        state, metrics = task.step_fn(state, b)
        # Only sync device→host on logging steps (async dispatch otherwise).
        if ctx.is_coordinator and ((step + 1) % log_every == 0
                                   or step + 1 == steps):
            emitter.emit(step, {k: float(v) for k, v in metrics.items()})
    emitter.close()
    return 0


@register_entrypoint("llm_pretrain")
def llm_pretrain(ctx: WorkerContext) -> int:
    import jax

    from kubeflow_tpu.train.trainer import Trainer, TrainerConfig

    cfg = TrainerConfig.from_dict(ctx.config)
    mesh = ctx.mesh
    if mesh is None:
        from kubeflow_tpu.runtime.bootstrap import single_worker_mesh

        mesh = single_worker_mesh(ctx.env, axis="fsdp")
    metrics_path = None
    if ctx.env.workdir:
        metrics_path = os.path.join(ctx.env.workdir, "metrics.jsonl")
    trainer = Trainer(
        cfg, mesh,
        process_id=ctx.env.process_id,
        num_processes=ctx.env.num_processes,
        metrics_path=metrics_path,
        workdir=ctx.env.workdir,
    )
    trainer.run()
    return 0
