"""Python SDK — the TrainingClient / KatibClient / kfp.Client analog.

((U) training-operator sdk/python kubeflow/training TrainingClient
{create_job,get_job,get_job_logs,wait_for_job_conditions,delete_job, train};
katib KatibClient.tune; kfp.Client.create_run — SURVEY.md §2.2#22, §2.4#36,
§2.5#37.) One client over the in-process control plane: the platform is
single-host, so the SDK talks to the store directly; the HTTP path for
remote callers is the CLI/ApiServer.
"""

from __future__ import annotations

import os
import time
from typing import Any, Optional

from kubeflow_tpu.core.jobs import (
    JAXJob, JAXJobSpec, ParallelismSpec, ReplicaSpec, TPUResourceSpec,
    WorkloadSpec,
)
from kubeflow_tpu.core.object import ApiObject, ObjectMeta
from kubeflow_tpu.core.pipeline_specs import (
    Pipeline, PipelineRun, PipelineRunSpec, PipelineSpecModel,
)


class Client:
    """SDK over a running ControlPlane (start one, or use ``local()``)."""

    def __init__(self, control_plane):
        self.cp = control_plane

    @classmethod
    def local(cls, base_dir: Optional[str] = None, platform: str = "cpu",
              num_chips: Optional[int] = None) -> "Client":
        """Spin up an in-process platform (caller owns .shutdown())."""
        from kubeflow_tpu.operator.control_plane import (
            ControlPlane, ControlPlaneConfig,
        )
        from kubeflow_tpu.runtime.topology import detect_local_cluster

        cluster = (detect_local_cluster(num_chips=num_chips)
                   if num_chips else None)
        cp = ControlPlane(ControlPlaneConfig(
            base_dir=base_dir, platform=platform, cluster=cluster))
        cp.start()
        return cls(cp)

    def shutdown(self) -> None:
        self.cp.stop()

    # -- training (TrainingClient surface) -------------------------------------

    def create_job(
        self,
        name: str,
        *,
        entrypoint: str = "llm_pretrain",
        config: Optional[dict[str, Any]] = None,
        workers: int = 1,
        chips_per_worker: int = 1,
        parallelism: Optional[dict[str, int]] = None,
        namespace: str = "default",
        submit: bool = True,
        **run_policy,
    ) -> JAXJob:
        job = JAXJob(
            metadata=ObjectMeta(name=name, namespace=namespace),
            spec=JAXJobSpec(
                replica_specs={"worker": ReplicaSpec(
                    replicas=workers,
                    template=WorkloadSpec(entrypoint=entrypoint,
                                          config=config or {}),
                    resources=TPUResourceSpec(tpu_chips=chips_per_worker))},
                parallelism=ParallelismSpec(**(parallelism or {})),
            ))
        for k, v in run_policy.items():
            setattr(job.spec.run_policy, k, v)
        return self.cp.submit(job) if submit else job

    def get_job(self, name: str, namespace: str = "default") -> Optional[JAXJob]:
        return self.cp.store.try_get(JAXJob, name, namespace)

    def get_job_logs(self, name: str, namespace: str = "default",
                     worker: int = 0, max_bytes: int = 65536) -> str:
        path = os.path.join(self.cp.config.base_dir, "logs",
                            f"{namespace}.{name}-worker-{worker}.log")
        try:
            with open(path, "rb") as f:
                f.seek(0, 2)
                f.seek(max(0, f.tell() - max_bytes))
                return f.read().decode(errors="replace")
        except OSError:
            return ""

    def wait_for_job_conditions(
        self, name: str, conditions=("Succeeded",),
        namespace: str = "default", timeout: float = 300.0,
    ) -> JAXJob:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            job = self.get_job(name, namespace)
            if job is not None:
                for c in conditions:
                    if job.status.has_condition(c):
                        return job
                if "Failed" not in conditions \
                        and job.status.has_condition("Failed"):
                    cond = job.status.get_condition("Failed")
                    raise RuntimeError(
                        f"job {name} failed: {cond.reason if cond else ''} "
                        f"{cond.message if cond else ''}")
            time.sleep(0.2)
        raise TimeoutError(f"job {name}: none of {conditions} in {timeout}s")

    def delete_job(self, name: str, namespace: str = "default") -> None:
        self.cp.store.delete(JAXJob, name, namespace)

    def train(
        self,
        name: str,
        *,
        model: str = "llama3-8b",
        model_overrides: Optional[dict] = None,
        steps: int = 100,
        workers: int = 1,
        chips_per_worker: int = 1,
        parallelism: Optional[dict[str, int]] = None,
        optimizer: Optional[dict] = None,
        data: Optional[dict] = None,
        dataset_uri: Optional[str] = None,
        tokenizer_uri: Optional[str] = None,
        train_tokenizer_vocab: Optional[int] = None,
        checkpoint: bool = True,
        namespace: str = "default",
        wait: bool = False,
        timeout: float = 3600.0,
    ) -> JAXJob:
        """High-level LLM training (TrainingClient.train analog — the
        reference downloads HF model+dataset into a PVC via its
        storage-initializer initContainer; ``dataset_uri`` stages the
        dataset into the job dir the same way, tokenizing through a staged
        or freshly-trained BPE artifact)."""
        config = {
            "model": model,
            "model_overrides": model_overrides or {},
            "steps": steps,
            "optimizer": optimizer or {},
            "data": data or {},
        }
        if dataset_uri:
            config["dataset_uri"] = dataset_uri
        if tokenizer_uri:
            config["tokenizer_uri"] = tokenizer_uri
        if train_tokenizer_vocab:
            config["train_tokenizer_vocab"] = train_tokenizer_vocab
        job = self.create_job(
            name,
            entrypoint="llm_pretrain",
            config=config,
            workers=workers, chips_per_worker=chips_per_worker,
            parallelism=parallelism, namespace=namespace,
            submit=False)   # finish the spec BEFORE the controller sees it
        job.spec.run_policy.checkpoint.enabled = checkpoint
        job = self.cp.submit(job)
        if wait:
            return self.wait_for_job_conditions(name, namespace=namespace,
                                                timeout=timeout)
        return job

    # -- HPO (KatibClient surface) ---------------------------------------------

    def tune(self, name: str, *, timeout: float = 600.0, **kwargs):
        from kubeflow_tpu.tune.client import tune as _tune

        return _tune(self.cp, name, timeout=timeout, **kwargs)

    # -- pipelines (kfp.Client surface) ----------------------------------------

    def upload_pipeline(self, pipeline_def, *, name: Optional[str] = None,
                        namespace: str = "default") -> Pipeline:
        from kubeflow_tpu.pipelines.compiler import as_pipeline_object

        return self.cp.apply(as_pipeline_object(
            pipeline_def, namespace=namespace, name=name))

    def create_run(self, pipeline: str, *, run_name: Optional[str] = None,
                   parameters: Optional[dict] = None,
                   namespace: str = "default", wait: bool = False,
                   timeout: float = 600.0) -> PipelineRun:
        run = PipelineRun(
            metadata=ObjectMeta(
                name=run_name or f"{pipeline}-{int(time.time())}",
                namespace=namespace),
            spec=PipelineRunSpec(pipeline=pipeline,
                                 parameters=parameters or {}))
        run = self.cp.submit(run)
        if wait:
            return self.cp.wait_for(run, "Succeeded", timeout=timeout)
        return run

    # -- artifacts (the train→deploy seam) -------------------------------------

    @property
    def artifacts(self):
        """The platform artifact store. ``publish_model(ckpt_dir,
        name=..., store=client.artifacts)`` → an ``artifact://`` uri usable
        as an InferenceService storageUri or a ``train()`` dataset_uri."""
        return self.cp.artifact_store

    def publish_model(self, checkpoint_dir: str, *, name=None,
                      version=None) -> str:
        from kubeflow_tpu.pipelines.artifacts import publish_model

        return publish_model(checkpoint_dir, name=name, version=version,
                             store=self.artifacts)

    def publish_file(self, path: str, *, name=None, version=None,
                     type_name: str = "Dataset") -> str:
        from kubeflow_tpu.pipelines.artifacts import publish_file

        return publish_file(path, name=name, version=version,
                            store=self.artifacts, type_name=type_name)

    # -- generic ---------------------------------------------------------------

    def apply(self, obj: ApiObject) -> ApiObject:
        return self.cp.apply(obj)

    def wait_for(self, obj: ApiObject, condition: str = "Succeeded",
                 timeout: float = 300.0) -> ApiObject:
        return self.cp.wait_for(obj, condition, timeout=timeout)
