"""kubeflow_tpu — a TPU-native ML platform with the Kubeflow capability surface.

A ground-up rebuild of the Kubeflow stack (training operator, serving, HPO,
pipelines, workspaces) designed TPU-first: declarative specs reconciled by
in-process controllers, a JAX/XLA SPMD data plane over `jax.sharding.Mesh`
(DP/FSDP/TP/PP/EP/SP), Pallas kernels for the hot ops, `jax.distributed`
bootstrap in place of NCCL/MPI rendezvous, and orbax checkpointing.

Capability parity map (see SURVEY.md §2; reference citations are upstream
symbols — the reference mount was empty at survey time, SURVEY.md §0):

- ``core``      — declarative API objects + object store (≈ pkg/apis/* + kube-apiserver)
- ``runtime``   — TPU slice topology, gang allocator, process manager (≈ scheduler/kubelet/volcano)
- ``models``    — Llama/Gemma/Mixtral/ViT/CLIP functional JAX models (data plane)
- ``ops``       — Pallas TPU kernels (flash/ring attention, rmsnorm, MoE dispatch)
- ``parallel``  — mesh/sharding policies, pipeline schedules, collectives
- ``train``     — train step, trainer loop, checkpointing, metrics
- ``operator``  — JAXJob controller (≈ kubeflow/training-operator)
- ``serve``     — continuous-batching inference engine + InferenceService (≈ kserve)
- ``tune``      — HPO experiments + suggestion algorithms (≈ kubeflow/katib)
- ``pipelines`` — DAG DSL/compiler/executor + metadata lineage (≈ kubeflow/pipelines + MLMD)
- ``workspace`` — notebook sessions, profiles, pod defaults (≈ kubeflow/kubeflow monorepo)
"""

__version__ = "0.1.0"

import os as _os

if _os.environ.get("KFTPU_SANITIZE", "").strip() not in ("", "0"):
    # Runtime sanitizers (ISSUEs 7/8): the lockorder watchdog must wrap
    # threading.Lock/RLock BEFORE any engine/router/controller constructs
    # its locks, and the recompile watchdog must be listening before the
    # first jit dispatch, so installation happens at package import. Free
    # when the env var is unset (the normal case never reaches this
    # import).
    from kubeflow_tpu.runtime import sanitize as _sanitize

    _sanitize.maybe_install()
