"""``kubeflow_tpu.analysis`` — the ``kftpu lint`` static analyzer.

See ``core.py`` for the framework (walker, annotation grammar, baseline),
``rules_device.py`` for the device-hygiene family (D1xx),
``rules_concurrency.py`` for the lock-discipline family (C3xx), and
``rules_metrics.py`` for the metric-name rules (M2xx).
"""

from kubeflow_tpu.analysis.core import (  # noqa: F401
    Baseline, Finding, LintResult, Module, Rule, all_rules, find_baseline,
    lint_source, main, run_lint,
)
