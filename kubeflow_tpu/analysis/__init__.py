"""``kubeflow_tpu.analysis`` — the ``kftpu lint`` static analyzer.

See ``core.py`` for the framework (walker, annotation grammar, baseline,
call graph + resource-pairing primitives, ``--changed``),
``rules_device.py`` for the device-hygiene family (D1xx),
``rules_concurrency.py`` for the lock-discipline family (C3xx),
``rules_metrics.py`` for the metric-name rules (M2xx),
``rules_sharding.py`` for the sharding/SPMD family (S4xx),
``rules_resources.py`` for the resource-pairing / lock-order family
(R5xx), ``rules_compile.py`` for the compilation-stability family
(F6xx, built on the whole-program ``Program`` call graph), and
``rules_contracts.py`` for the cross-component name-contract family
(X7xx: metric series produced vs consumed, ``X-Kftpu-*`` headers set vs
read, ``KFTPU_*`` env vars, status fields — ``--contracts-json`` dumps
the extracted table), and ``rules_liveness.py`` for the
distributed-liveness family (T8xx: unbounded blocking calls, ad-hoc
retry loops, leaked/unreapable threads, deadline-propagation drift —
``# blocking-ok: <reason>`` closes deliberate waits). The runtime
cross-checks (``KFTPU_SANITIZE=
refcount|lockorder|recompile|contract|threads``) live in
``kubeflow_tpu/runtime/sanitize.py``.
"""

from kubeflow_tpu.analysis.core import (  # noqa: F401
    Baseline, Finding, JitFact, LintResult, Module, Program, Rule,
    all_rules, build_program, canonical_mesh_axes, changed_files,
    find_baseline, jit_table, lint_source, lint_sources, main, run_lint,
)
