"""Family F — compilation-stability rules (ISSUE 8 tentpole).

The jit cache is keyed by the full dispatch signature: argument shapes,
dtypes, weak-type flags, static-arg hashes, and pytree structure. A
change in ANY of them silently recompiles — minutes per retrace at
supercluster scale (ROADMAP open item 4), and a recompile storm in the
decode hot loop erases the PR-4 host-overhead win. These rules encode
the dispatch contracts the engine already follows (pow2-padded tables,
``jnp.asarray(..., dtype=)`` at upload sites, jit ctors built once with
explicit ``static_argnums``) and fail anything that drifts from them:

- F601 ``unstable-trace-shape``: a jitted callable dispatched with an
  array whose shape derives from ``len()``/``qsize()`` (list growth,
  non-padded batch state) rather than a padded/bucketed size — every
  distinct length is a fresh trace.
- F602 ``weak-type-leak``: a Python scalar (literal, ``float()``/
  ``int()`` result, ``.item()`` fetch) riding into a NON-static arg of a
  jitted call without an explicit dtype — weak-typed avals are their own
  cache entries, doubling the trace set per scalar source.
- F603 ``dtype-promotion-drift``: call sites of the same jitted callable
  pin DIFFERENT explicit dtypes onto the same argument position (f32 at
  one site, bf16 at another) — each promoted signature compiles
  separately, and the numerics silently differ between them.
- F604 ``static-arg-instability``: a ``static_argnums`` position fed a
  value rebuilt per call with unstable hash/identity — a tuple literal
  holding runtime values, a fresh ``lambda``, a ``functools.partial`` —
  forcing a retrace (or an unbounded cache) per dispatch.
- F605 ``pytree-structure-instability``: the dict/state-dict argument of
  a jitted callable changes STRUCTURE between dispatches — different
  literal key sets across call sites, or keys inserted conditionally
  before the dispatch — a new pytree treedef is a new compile.

Escapes: ``# retrace-ok: <reason>`` on the call-site line marks an
intentional cold-path instability; ``# lint: disable=F60x`` suppresses a
single rule. With a whole-program ``Program`` attached (core.py), jit
facts imported from other ``kubeflow_tpu/*`` modules carry their
static/donate argument specs to call sites here; standalone fixtures
degrade to module-local facts.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from kubeflow_tpu.analysis.core import (
    Finding, JitFact, Module, Rule, jit_table, register,
)

_LEN_QNS = {"len"}
_LEN_METHODS = {"qsize"}
_SHAPE_CTORS = {
    "numpy.zeros", "numpy.ones", "numpy.full", "numpy.empty",
    "jax.numpy.zeros", "jax.numpy.ones", "jax.numpy.full",
    "jax.numpy.empty",
}
_ASARRAY_QNS = {"jax.numpy.asarray", "jax.numpy.array"}
_DTYPE_CTOR_SUFFIXES = {
    "float32", "float64", "bfloat16", "float16", "int32", "int64",
    "int16", "int8", "uint8", "uint32", "bool_",
}
#: Size-stabilizing spellings: a value produced by one of these is a
#: padded/bucketed size even when its input was len-derived (the
#: engine's pow2 pad loops assign through these helpers or compare
#: against the tainted var without ever being assigned FROM it).
_STABILIZER_MARKERS = ("pad", "bucket", "pow2", "align")


def _facts_for(mod: Module) -> dict[str, JitFact]:
    if mod.program is not None:
        return mod.program.jit_facts(mod)
    return jit_table(mod)


def _expr_key(node: ast.AST) -> Optional[str]:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        return ".".join([node.id] + list(reversed(parts)))
    return None


def _functions(mod: Module) -> Iterable[ast.AST]:
    for node in mod.walk():
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _jit_calls(mod: Module, fn: ast.AST,
               facts: dict[str, JitFact]
               ) -> Iterable[tuple[ast.Call, JitFact]]:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            key = _expr_key(node.func)
            if key in facts:
                yield node, facts[key]


def _retrace_ok(mod: Module, line: int) -> bool:
    return (mod.line_annotation(line, "retrace_ok") is not None
            or mod.line_annotation(line - 1, "retrace_ok") is not None)


def _static_positions(fact: JitFact) -> frozenset:
    return frozenset(fact.static_argnums)


def _mentions(node: ast.AST, names: set[str]) -> Optional[str]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in names:
            return sub.id
    return None


def _is_stabilizer_call(call: ast.Call) -> bool:
    key = (_expr_key(call.func) or "").lower()
    return any(m in key for m in _STABILIZER_MARKERS)


# -- F601 ----------------------------------------------------------------------


def _len_taint(mod: Module, fn: ast.AST) -> tuple[set[str], set[str]]:
    """(tainted scalar names, unstable-shaped array names) for one
    function: vars holding ``len()``-class sizes, and arrays whose shape
    was built from them. Two passes so one-hop chains propagate."""
    tainted: set[str] = set()
    unstable: set[str] = set()
    assigns = [n for n in ast.walk(fn) if isinstance(n, ast.Assign)
               and len(n.targets) == 1
               and isinstance(n.targets[0], ast.Name)]
    for _ in range(2):
        for node in assigns:
            name = node.targets[0].id
            val = node.value
            if isinstance(val, ast.Call):
                qn = mod.qualname(val.func)
                is_len = qn in _LEN_QNS or (
                    isinstance(val.func, ast.Attribute)
                    and val.func.attr in _LEN_METHODS)
                if is_len:
                    tainted.add(name)
                    continue
                if _is_stabilizer_call(val):
                    tainted.discard(name)
                    continue
                if qn in _SHAPE_CTORS:
                    shape_args = list(val.args[:1]) + [
                        kw.value for kw in val.keywords
                        if kw.arg in ("shape", "size")]
                    if any(_mentions(a, tainted) for a in shape_args):
                        unstable.add(name)
                    continue
                # jnp.asarray(unstable) and friends keep the shape
                if any(_mentions(a, unstable) for a in val.args):
                    unstable.add(name)
                continue
            if _mentions(val, tainted):
                tainted.add(name)
            elif _mentions(val, unstable):
                unstable.add(name)
    return tainted, unstable


def _slice_taint(node: ast.AST, tainted: set[str]) -> Optional[str]:
    """A subscript slice whose bound mentions a tainted size
    (``arr[:n]``) produces an unstable-shaped view."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Subscript) and isinstance(sub.slice,
                                                         ast.Slice):
            for bound in (sub.slice.lower, sub.slice.upper):
                if bound is not None:
                    hit = _mentions(bound, tainted)
                    if hit:
                        return hit
    return None


@register
class UnstableTraceShape(Rule):
    id = "F601"
    name = "unstable-trace-shape"
    doc = ("jitted callable dispatched with an array whose shape derives "
           "from len()/list growth instead of a padded/bucketed size — "
           "every distinct length is a fresh trace")

    def check(self, mod: Module) -> Iterable[Finding]:
        facts = _facts_for(mod)
        if not facts:
            return
        for fn in _functions(mod):
            tainted, unstable = _len_taint(mod, fn)
            if not tainted and not unstable:
                continue
            for call, fact in _jit_calls(mod, fn, facts):
                if _retrace_ok(mod, call.lineno):
                    continue
                static = _static_positions(fact)
                for i, arg in enumerate(call.args):
                    if i in static:
                        continue
                    hit = _mentions(arg, unstable)
                    what = hit and (f"array '{hit}', whose shape was "
                                    "built from a len-like size")
                    if hit is None:
                        hit = _slice_taint(arg, tainted)
                        what = hit and (f"a slice bounded by len-like "
                                        f"size '{hit}'")
                    if hit is None and isinstance(arg, ast.Call):
                        qn = mod.qualname(arg.func)
                        if qn in _SHAPE_CTORS:
                            hit = _mentions(arg, tainted)
                            what = hit and (f"an array shaped inline by "
                                            f"len-like size '{hit}'")
                    if hit is None:
                        continue
                    yield mod.finding(
                        self, call,
                        f"'{fact.name}' is dispatched with {what}; every "
                        "distinct length is a fresh trace — pad to a "
                        "pow2/bucketed width so the trace set stays "
                        "log-bounded")
                    break


# -- F602 ----------------------------------------------------------------------


def _scalar_taint(mod: Module, fn: ast.AST) -> set[str]:
    """Names holding Python scalars: numeric literals, ``float()``/
    ``int()`` results, ``.item()`` fetches, arithmetic over those."""
    tainted: set[str] = set()
    assigns = [n for n in ast.walk(fn) if isinstance(n, ast.Assign)
               and len(n.targets) == 1
               and isinstance(n.targets[0], ast.Name)]
    for _ in range(2):
        for node in assigns:
            name = node.targets[0].id
            val = node.value
            if _is_py_scalar(mod, val, tainted):
                tainted.add(name)
            elif isinstance(val, ast.Name) or isinstance(val, ast.Call):
                tainted.discard(name)
    return tainted


def _is_py_scalar(mod: Module, node: ast.AST, tainted: set[str]) -> bool:
    if isinstance(node, ast.Constant):
        # bools are int subclasses but carry a 2-entry cache at most and
        # are usually intentional mode flags — not worth the noise.
        return isinstance(node.value, (int, float)) \
            and not isinstance(node.value, bool)
    if isinstance(node, ast.Name):
        return node.id in tainted
    if isinstance(node, ast.UnaryOp):
        return _is_py_scalar(mod, node.operand, tainted)
    if isinstance(node, ast.BinOp):
        return _is_py_scalar(mod, node.left, tainted) \
            and _is_py_scalar(mod, node.right, tainted)
    if isinstance(node, ast.Call):
        if isinstance(node.func, ast.Name) \
                and node.func.id in ("float", "int"):
            return True
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "item" and not node.args:
            return True
    return False


def _has_explicit_dtype(call: ast.Call) -> bool:
    return len(call.args) >= 2 or any(kw.arg == "dtype"
                                      for kw in call.keywords)


def _is_dtype_ctor(mod: Module, call: ast.Call) -> bool:
    qn = mod.qualname(call.func) or ""
    return qn.rsplit(".", 1)[-1] in _DTYPE_CTOR_SUFFIXES


@register
class WeakTypeLeak(Rule):
    id = "F602"
    name = "weak-type-leak"
    doc = ("Python scalar flowing into a non-static arg of a jitted call "
           "without an explicit dtype — each distinct weak type is a "
           "separate compile-cache entry")

    def check(self, mod: Module) -> Iterable[Finding]:
        facts = _facts_for(mod)
        if not facts:
            return
        for fn in _functions(mod):
            tainted = _scalar_taint(mod, fn)
            for call, fact in _jit_calls(mod, fn, facts):
                if _retrace_ok(mod, call.lineno):
                    continue
                static = _static_positions(fact)
                for i, arg in enumerate(call.args):
                    if i in static:
                        continue
                    leak = self._weak_leak(mod, arg, tainted)
                    if leak is None:
                        continue
                    yield mod.finding(
                        self, call,
                        f"{leak} rides into jitted '{fact.name}' "
                        f"(arg {i}) as a weak-typed scalar; wrap it "
                        "jnp.asarray(..., dtype=...) so the dispatch "
                        "signature is one cache entry, not one per "
                        "Python type")
                for kw in call.keywords:
                    if kw.arg in fact.static_argnames or kw.arg is None:
                        continue
                    leak = self._weak_leak(mod, kw.value, tainted)
                    if leak is None:
                        continue
                    yield mod.finding(
                        self, call,
                        f"{leak} rides into jitted '{fact.name}' "
                        f"(kwarg '{kw.arg}') as a weak-typed scalar; "
                        "wrap it jnp.asarray(..., dtype=...)")

    def _weak_leak(self, mod: Module, arg: ast.AST,
                   tainted: set[str]) -> Optional[str]:
        """Human-readable description of the weak-typed payload, or None
        when the arg is dtype-stable."""
        if isinstance(arg, ast.Call):
            if _is_dtype_ctor(mod, arg):
                return None
            qn = mod.qualname(arg.func)
            if qn in _ASARRAY_QNS:
                if _has_explicit_dtype(arg):
                    return None
                if arg.args and _is_py_scalar(mod, arg.args[0], tainted):
                    return ("a Python scalar through dtype-less "
                            "jnp.asarray")
                return None
        if _is_py_scalar(mod, arg, tainted):
            if isinstance(arg, ast.Constant):
                return f"literal {arg.value!r}"
            if isinstance(arg, ast.Name):
                return f"host scalar '{arg.id}'"
            return "a host-computed Python scalar"
        return None


# -- F603 ----------------------------------------------------------------------


def _dtype_token(mod: Module, arg: ast.AST) -> Optional[str]:
    """The explicit dtype a call site pins onto an argument, as a short
    token ('float32'), or None when no explicit dtype is visible."""
    if not isinstance(arg, ast.Call):
        return None
    if _is_dtype_ctor(mod, arg):
        return (mod.qualname(arg.func) or "").rsplit(".", 1)[-1]
    qn = mod.qualname(arg.func)
    dnode: Optional[ast.AST] = None
    if qn in _ASARRAY_QNS or (qn or "").endswith(("asarray", "array")):
        if len(arg.args) >= 2:
            dnode = arg.args[1]
        for kw in arg.keywords:
            if kw.arg == "dtype":
                dnode = kw.value
    elif isinstance(arg.func, ast.Attribute) and arg.func.attr == "astype" \
            and arg.args:
        dnode = arg.args[0]
    if dnode is None:
        return None
    if isinstance(dnode, ast.Constant) and isinstance(dnode.value, str):
        return dnode.value
    key = _expr_key(dnode)
    if key:
        suffix = key.rsplit(".", 1)[-1]
        if suffix in _DTYPE_CTOR_SUFFIXES or suffix.startswith(
                ("float", "int", "uint", "bfloat", "bool")):
            return suffix
    return None


@register
class DtypePromotionDrift(Rule):
    id = "F603"
    name = "dtype-promotion-drift"
    doc = ("call sites of one jitted callable pin different explicit "
           "dtypes onto the same argument position — each promoted "
           "signature is its own compile-cache entry")

    def check(self, mod: Module) -> Iterable[Finding]:
        facts = _facts_for(mod)
        if not facts:
            return
        # (callable name, arg position) -> {dtype token: first call}
        seen: dict[tuple[str, int], dict[str, ast.Call]] = {}
        for fn in _functions(mod):
            for call, fact in _jit_calls(mod, fn, facts):
                static = _static_positions(fact)
                for i, arg in enumerate(call.args):
                    if i in static:
                        continue
                    tok = _dtype_token(mod, arg)
                    if tok is None:
                        continue
                    slot = seen.setdefault((fact.name, i), {})
                    if tok not in slot:
                        slot[tok] = call
                    if len(slot) >= 2 and not _retrace_ok(mod,
                                                          call.lineno):
                        others = sorted(t for t in slot if t != tok)
                        yield mod.finding(
                            self, call,
                            f"arg {i} of jitted '{fact.name}' is "
                            f"'{tok}' here but {', '.join(others)!s} at "
                            "another call site; the promoted dtype "
                            "differs per site, so each dispatches a "
                            "separate compiled program")


# -- F604 ----------------------------------------------------------------------


@register
class StaticArgInstability(Rule):
    id = "F604"
    name = "static-arg-instability"
    doc = ("a static_argnums position fed a value rebuilt per call "
           "(tuple of runtime values, fresh lambda, functools.partial) — "
           "a retrace per dispatch")

    def check(self, mod: Module) -> Iterable[Finding]:
        facts = _facts_for(mod)
        if not facts:
            return
        for fn in _functions(mod):
            for call, fact in _jit_calls(mod, fn, facts):
                if _retrace_ok(mod, call.lineno):
                    continue
                spots = [(f"arg {i}", call.args[i])
                         for i in fact.static_argnums
                         if i < len(call.args)]
                spots += [(f"kwarg '{kw.arg}'", kw.value)
                          for kw in call.keywords
                          if kw.arg in fact.static_argnames]
                for label, arg in spots:
                    why = self._unstable(arg)
                    if why is None:
                        continue
                    yield mod.finding(
                        self, call,
                        f"static {label} of jitted '{fact.name}' is "
                        f"{why}; the jit cache hashes static args, so a "
                        "per-call value means a retrace per dispatch — "
                        "hoist it or make it a traced arg")

    def _unstable(self, arg: ast.AST) -> Optional[str]:
        if isinstance(arg, (ast.Tuple, ast.List)):
            if any(not isinstance(e, ast.Constant) for e in arg.elts):
                return "a tuple rebuilt from runtime values every call"
            return None
        if isinstance(arg, ast.Lambda):
            return "a fresh lambda (hashed by identity) every call"
        if isinstance(arg, ast.Call):
            qn = _expr_key(arg.func) or ""
            if qn in ("functools.partial", "partial"):
                return "a fresh functools.partial (hashed by identity)"
        return None


# -- F605 ----------------------------------------------------------------------


def _literal_keys(node: ast.AST) -> Optional[frozenset]:
    """Key set of a dict literal with all-constant-string keys; None for
    anything with ``**`` spreads or computed keys (opaque — the engine's
    ``{**st, "tokens": ...}`` rebuilds are structure-preserving by
    construction)."""
    if not isinstance(node, ast.Dict):
        return None
    keys: set[str] = set()
    for k in node.keys:
        if k is None:       # ** spread
            return None
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
            return None
        keys.add(k.value)
    return frozenset(keys)


@register
class PytreeStructureInstability(Rule):
    id = "F605"
    name = "pytree-structure-instability"
    doc = ("the dict argument of a jitted callable changes structure "
           "between dispatches (different key sets across call sites, "
           "or keys inserted conditionally) — a new treedef is a new "
           "compile")

    def check(self, mod: Module) -> Iterable[Finding]:
        facts = _facts_for(mod)
        if not facts:
            return
        # Part (a): literal key sets per (callable, position) across the
        # module's call sites.
        seen: dict[tuple[str, int], dict[frozenset, ast.Call]] = {}
        for fn in _functions(mod):
            for call, fact in _jit_calls(mod, fn, facts):
                static = _static_positions(fact)
                for i, arg in enumerate(call.args):
                    if i in static:
                        continue
                    keys = _literal_keys(arg)
                    if keys is None:
                        continue
                    slot = seen.setdefault((fact.name, i), {})
                    if keys not in slot:
                        slot[keys] = call
                    if len(slot) >= 2 and not _retrace_ok(mod,
                                                          call.lineno):
                        other = next(k for k in slot if k != keys)
                        diff = sorted(keys ^ other)
                        yield mod.finding(
                            self, call,
                            f"dict arg {i} of jitted '{fact.name}' has "
                            f"keys {sorted(keys)} here but a different "
                            f"set at another call site (diff: {diff}); "
                            "pytree structure is part of the dispatch "
                            "signature — keep one treedef")
            yield from self._conditional_inserts(mod, fn, facts)

    def _conditional_inserts(self, mod: Module, fn: ast.AST,
                             facts: dict[str, JitFact]
                             ) -> Iterable[Finding]:
        """Part (b): ``d = {...}`` then ``d["k"] = ...`` under an ``if``
        (a key present only on some paths) before ``d`` rides into a
        jitted dispatch."""
        literals: dict[str, frozenset] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                keys = _literal_keys(node.value)
                if keys is not None:
                    literals[node.targets[0].id] = keys
        if not literals:
            return
        unstable: dict[str, ast.AST] = {}
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Subscript)):
                continue
            sub = node.targets[0]
            if not isinstance(sub.value, ast.Name) \
                    or sub.value.id not in literals:
                continue
            key = sub.slice
            if isinstance(key, ast.Constant) and isinstance(key.value, str) \
                    and key.value in literals[sub.value.id]:
                continue        # value update, structure unchanged
            cond = self._conditional_ancestor(node, fn)
            if cond is not None:
                unstable.setdefault(sub.value.id, cond)
        if not unstable:
            return
        for call, fact in _jit_calls(mod, fn, facts):
            if _retrace_ok(mod, call.lineno):
                continue
            for i, arg in enumerate(call.args):
                if i in _static_positions(fact):
                    continue
                if isinstance(arg, ast.Name) and arg.id in unstable:
                    cond = unstable[arg.id]
                    if self._contains(cond, call):
                        continue    # same branch: structure fixed there
                    yield mod.finding(
                        self, call,
                        f"dict '{arg.id}' gains a key only on some "
                        f"paths (conditional insert at line "
                        f"{cond.lineno}) before dispatching jitted "
                        f"'{fact.name}'; the treedef flips between "
                        "dispatches — build both structures as one "
                        "literal")

    @staticmethod
    def _conditional_ancestor(node: ast.AST, fn: ast.AST
                              ) -> Optional[ast.AST]:
        cur = getattr(node, "_parent", None)
        while cur is not None and cur is not fn:
            if isinstance(cur, (ast.If, ast.While, ast.For)):
                return cur
            cur = getattr(cur, "_parent", None)
        return None

    @staticmethod
    def _contains(ancestor: ast.AST, node: ast.AST) -> bool:
        cur: Optional[ast.AST] = node
        while cur is not None:
            if cur is ancestor:
                return True
            cur = getattr(cur, "_parent", None)
        return False
