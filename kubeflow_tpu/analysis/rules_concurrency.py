"""Family B — lock-discipline / concurrency rules (TSan-style, static).

The platform's control plane is threads all the way down: the engine
scheduler thread, the router's per-request handler threads, controller
event/worker pairs, the ISVC autoscaler. PR 2's chaos harness catches
unlocked shared mutation only probabilistically; these rules catch it from
the AST:

- C301 ``unlocked-shared-mutation``: per class, infer the lock attributes
  (``threading.Lock``/``RLock``/``Condition`` assigned in ``__init__``)
  and the thread entry points (``Thread(target=self.m)``, executor
  ``submit(self.m)``); flag attributes mutated without a lock held from a
  thread-reachable method while also being accessed from the public
  surface. The ``# guarded_by: <lock>`` annotation turns an attribute
  into a checked contract (every mutation must hold that lock);
  ``# lockfree: <reason>`` documents deliberate confinement and closes
  the false positive. Methods named ``*_locked`` or annotated
  ``# requires_lock: <lock>`` count as holding the lock (callers do).
- C302 ``blocking-call-under-lock``: ``time.sleep``, socket/HTTP I/O,
  ``subprocess``, ``Thread.join`` or ``Event.wait`` while a lock is held
  (``Condition.wait`` is exempt — it releases the lock).
- C303 ``swallowed-exception``: a bare/broad ``except`` whose body
  neither re-raises nor calls anything (no logging, no status update) —
  the controller-killing silent failure.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable, Optional

from kubeflow_tpu.analysis.core import Finding, Module, Rule, register

_LOCK_TYPES = {"threading.Lock", "threading.RLock"}
_COND_TYPES = {"threading.Condition"}
_EXEMPT_TYPES = {
    # objects that own their synchronization (or are immutable-ish)
    "threading.Event", "threading.Semaphore", "threading.BoundedSemaphore",
    "threading.local", "queue.Queue", "queue.SimpleQueue",
    "queue.LifoQueue", "queue.PriorityQueue", "itertools.count",
    "contextvars.ContextVar", "collections.OrderedDict",
}
_MUTATING_METHODS = {
    "append", "extend", "insert", "remove", "pop", "popleft", "popitem",
    "clear", "add", "discard", "update", "setdefault", "sort", "reverse",
}
_BLOCKING_CALLS = {
    "time.sleep", "urllib.request.urlopen", "socket.create_connection",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.Popen",
    "http.client.HTTPConnection", "requests.get", "requests.post",
    "requests.request",
}


def _self_attr_name(node: ast.AST) -> Optional[str]:
    """'X' for a plain ``self.X`` expression."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


@dataclasses.dataclass
class _Access:
    attr: str
    method: str
    node: ast.AST
    write: bool
    locks_held: frozenset  # lock attr names lexically held at the site


class _ClassModel:
    """Everything C301/C302 need to know about one class."""

    def __init__(self, mod: Module, cls: ast.ClassDef):
        self.mod = mod
        self.cls = cls
        self.methods: dict[str, ast.FunctionDef] = {}
        for stmt in cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[stmt.name] = stmt
        self.lock_attrs: set[str] = set()
        self.cond_to_lock: dict[str, str] = {}
        self.exempt_attrs: set[str] = set()
        self.container_attrs: set[str] = set()
        self.attr_guarded_by: dict[str, str] = {}
        self.attr_lockfree: set[str] = set()
        self.attr_init_node: dict[str, ast.AST] = {}
        self._scan_init()
        self.thread_entries = self._find_thread_entries()
        self.calls = {name: self._self_calls(fn)
                      for name, fn in self.methods.items()}
        self.thread_reachable = self._closure(self.thread_entries)
        public = {n for n in self.methods
                  if not n.startswith("_") and n != "__init__"}
        self.public_reachable = self._closure(public)
        # One-level caller-held inference (ISSUE 7): a private helper whose
        # EVERY same-class call site lexically holds lock L runs under L —
        # its accesses count as guarded without a # requires_lock:
        # annotation. Only ever silences C301, never invents a finding.
        self.caller_locks = self._infer_caller_locks()
        self.accesses: list[_Access] = []
        for name, fn in self.methods.items():
            if name == "__init__":
                continue
            self._collect_accesses(name, fn)

    # -- __init__ scan -----------------------------------------------------

    def _scan_init(self) -> None:
        init = self.methods.get("__init__")
        if init is None:
            return
        for stmt in ast.walk(init):
            if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                continue
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
            value = stmt.value
            for t in targets:
                attr = _self_attr_name(t)
                if attr is None:
                    continue
                self.attr_init_node.setdefault(attr, stmt)
                gb = self.mod.annotation(stmt, "guarded_by")
                if gb:
                    self.attr_guarded_by[attr] = gb
                if self.mod.annotation(stmt, "lockfree") is not None:
                    self.attr_lockfree.add(attr)
                if isinstance(value, ast.Call):
                    qn = self.mod.qualname(value.func)
                    if qn in _LOCK_TYPES:
                        self.lock_attrs.add(attr)
                    elif qn in _COND_TYPES:
                        self.lock_attrs.add(attr)
                        if value.args:
                            inner = _self_attr_name(value.args[0])
                            if inner:
                                self.cond_to_lock[attr] = inner
                    elif qn in _EXEMPT_TYPES:
                        self.exempt_attrs.add(attr)
                    elif qn in ("list", "dict", "set", "collections.deque"):
                        self.container_attrs.add(attr)
                elif isinstance(value, (ast.List, ast.Dict, ast.Set,
                                        ast.ListComp, ast.DictComp,
                                        ast.SetComp)):
                    self.container_attrs.add(attr)

    # -- thread entries / call graph ---------------------------------------

    def _find_thread_entries(self) -> set[str]:
        entries: set[str] = set()
        for fn in self.methods.values():
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                qn = self.mod.qualname(node.func)
                if qn in ("threading.Thread", "threading.Timer"):
                    for kw in node.keywords:
                        if kw.arg == "target":
                            m = _self_attr_name(kw.value)
                            if m and m in self.methods:
                                entries.add(m)
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "submit" and node.args:
                    m = _self_attr_name(node.args[0])
                    if m and m in self.methods:
                        entries.add(m)
        return entries

    def _self_calls(self, fn: ast.AST) -> set[str]:
        out: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                m = _self_attr_name(node.func)
                if m and m in self.methods:
                    out.add(m)
            elif isinstance(node, ast.Attribute):
                # bound-method references (callbacks) count as calls
                m = _self_attr_name(node)
                if m and m in self.methods:
                    out.add(m)
        return out

    def _closure(self, roots: set[str]) -> set[str]:
        seen = set()
        stack = [r for r in roots if r in self.methods]
        while stack:
            cur = stack.pop()
            if cur in seen:
                continue
            seen.add(cur)
            stack.extend(self.calls.get(cur, ()))
        return seen

    # -- lock-held tracking ------------------------------------------------

    def _canonical_lock(self, attr: str) -> str:
        return self.cond_to_lock.get(attr, attr)

    def _method_locks(self, name: str, fn: ast.AST) -> frozenset:
        """Locks the method body holds throughout (caller-held)."""
        held: set[str] = set()
        ann = self.mod.annotation(fn, "requires_lock")
        if ann:
            held.add(self._canonical_lock(ann))
        elif name.endswith("_locked") and self.lock_attrs:
            # codebase convention: *_locked methods run under the class's
            # (sole) lock; with several locks the annotation is required
            held.update(self._canonical_lock(a) for a in self.lock_attrs)
        held.update(getattr(self, "caller_locks", {}).get(name, ()))
        return frozenset(held)

    def _infer_caller_locks(self) -> dict[str, frozenset]:
        """method -> locks held at EVERY same-class call site. Private,
        non-thread-entry methods only (public ones are callable from
        outside, thread targets start lock-free)."""
        sites: dict[str, list[frozenset]] = {}

        def visit(node: ast.AST, held: frozenset) -> None:
            if isinstance(node, ast.With):
                extra = set()
                for item in node.items:
                    a = _self_attr_name(item.context_expr)
                    if a:
                        extra.add(self._canonical_lock(a))
                inner = frozenset(held | extra)
                for child in node.body:
                    visit(child, inner)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return
            if isinstance(node, ast.Call):
                m = _self_attr_name(node.func)
                if m and m in self.methods:
                    sites.setdefault(m, []).append(held)
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for name, fn in self.methods.items():
            base = frozenset()
            ann = self.mod.annotation(fn, "requires_lock")
            if ann:
                base = frozenset({self._canonical_lock(ann)})
            elif name.endswith("_locked") and self.lock_attrs:
                base = frozenset(self._canonical_lock(a)
                                 for a in self.lock_attrs)
            for stmt in fn.body:
                visit(stmt, base)
        out: dict[str, frozenset] = {}
        for m, held_sets in sites.items():
            if not m.startswith("_") or m in self.thread_entries:
                continue
            common = frozenset.intersection(*held_sets)
            if common:
                out[m] = common
        return out

    def _collect_accesses(self, method: str, fn: ast.FunctionDef) -> None:
        base = self._method_locks(method, fn)

        def visit(node: ast.AST, held: frozenset) -> None:
            if isinstance(node, ast.With):
                extra = set()
                for item in node.items:
                    # Liberal here (vs C302): ANY `with self.X:` counts as
                    # acquiring X — the lock may be inherited from a base
                    # class this module model cannot see (e.g. Metric's
                    # _lock under Histogram), and presuming a guard only
                    # ever silences C301, never invents a finding.
                    a = _self_attr_name(item.context_expr)
                    if a:
                        extra.add(self._canonical_lock(a))
                inner = frozenset(held | extra)
                for child in node.body:
                    visit(child, inner)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return      # nested defs analyzed separately (if methods)
            self._record(node, method, held)
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in fn.body:
            visit(stmt, base)

    def _record(self, node: ast.AST, method: str, held: frozenset) -> None:
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                attr, sub = self._target_attr(t)
                if attr:
                    self.accesses.append(
                        _Access(attr, method, node, True, held))
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                attr, _ = self._target_attr(t)
                if attr:
                    self.accesses.append(
                        _Access(attr, method, node, True, held))
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATING_METHODS:
            attr = _self_attr_name(node.func.value)
            if attr and attr in self.container_attrs:
                self.accesses.append(
                    _Access(attr, method, node, True, held))
        elif isinstance(node, ast.Attribute) \
                and isinstance(node.ctx, ast.Load):
            attr = _self_attr_name(node)
            if attr:
                self.accesses.append(
                    _Access(attr, method, node, False, held))

    @staticmethod
    def _target_attr(t: ast.AST) -> tuple[Optional[str], bool]:
        """('X', is_subscript) for targets ``self.X`` / ``self.X[...]``;
        tuple targets are handled by the caller walking elements."""
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                attr, sub = _ClassModel._target_attr(e)
                if attr:
                    return attr, sub
            return None, False
        if isinstance(t, ast.Subscript):
            return _self_attr_name(t.value), True
        a = _self_attr_name(t)
        return a, False


def class_models(mod: Module) -> list[_ClassModel]:
    # Building a _ClassModel walks every method several times; three rule
    # families consult it, so it rides the per-module memo (ISSUE 8's
    # parse-once contract) instead of being rebuilt per rule.
    return mod.memo("class_models", lambda m: [
        _ClassModel(m, node) for node in m.walk()
        if isinstance(node, ast.ClassDef)])


@register
class UnlockedSharedMutation(Rule):
    id = "C301"
    name = "unlocked-shared-mutation"
    doc = ("class attribute mutated without its lock while shared across "
           "threads; annotate '# guarded_by: <lock>' or "
           "'# lockfree: <reason>' on the __init__ assignment")

    def check(self, mod: Module) -> Iterable[Finding]:
        for cm in class_models(mod):
            yield from self._check_class(mod, cm)

    def _check_class(self, mod: Module, cm: _ClassModel
                     ) -> Iterable[Finding]:
        cls = cm.cls.name
        by_attr: dict[str, list[_Access]] = {}
        for acc in cm.accesses:
            by_attr.setdefault(acc.attr, []).append(acc)
        for attr, accs in sorted(by_attr.items()):
            if attr in cm.lock_attrs or attr in cm.exempt_attrs:
                continue
            if attr in cm.attr_lockfree:
                continue
            writes = [a for a in accs if a.write]
            if not writes:
                continue
            guard = cm.attr_guarded_by.get(attr)
            if guard is not None:
                lock = cm.cond_to_lock.get(guard, guard)
                for a in writes:
                    if lock not in a.locks_held:
                        yield mod.finding(
                            self, a.node,
                            f"'{cls}.{attr}' is declared "
                            f"'# guarded_by: {guard}' but is mutated in "
                            f"'{a.method}' without holding "
                            f"'self.{guard}'",
                            symbol=f"{cls}.{attr}")
                continue
            # inference mode: needs real threads + cross-surface sharing
            if not cm.thread_entries:
                continue
            t_writes = [a for a in writes
                        if a.method in cm.thread_reachable
                        and not a.locks_held]
            if not t_writes:
                continue
            p_access = [a for a in accs
                        if a.method in cm.public_reachable
                        and not a.locks_held]
            if not p_access:
                continue
            a = t_writes[0]
            other = next((x.method for x in p_access
                          if x.method != a.method), p_access[0].method)
            yield mod.finding(
                self, a.node,
                f"'{cls}.{attr}' is mutated in thread-reachable "
                f"'{a.method}' without a lock and also accessed from "
                f"the public surface ('{other}'); lock it or annotate "
                "'# guarded_by:'/'# lockfree:' on its __init__ "
                "assignment",
                symbol=f"{cls}.{attr}")


@register
class BlockingCallUnderLock(Rule):
    id = "C302"
    name = "blocking-call-under-lock"
    doc = ("sleep / network / subprocess / join / Event.wait while "
           "holding a lock")

    def check(self, mod: Module) -> Iterable[Finding]:
        for cm in class_models(mod):
            if not cm.lock_attrs:
                continue
            for name, fn in cm.methods.items():
                yield from self._check_method(mod, cm, name, fn)

    def _check_method(self, mod: Module, cm: _ClassModel, name: str,
                      fn: ast.FunctionDef) -> Iterable[Finding]:
        base = cm._method_locks(name, fn)

        def visit(node: ast.AST, held: frozenset) -> Iterable[Finding]:
            if isinstance(node, ast.With):
                extra = set()
                for item in node.items:
                    a = _self_attr_name(item.context_expr)
                    if a and a in cm.lock_attrs:
                        extra.add(cm._canonical_lock(a))
                inner = frozenset(held | extra)
                for child in node.body:
                    yield from visit(child, inner)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return
            if held and isinstance(node, ast.Call):
                hit = self._blocking(mod, cm, node)
                if hit:
                    yield mod.finding(
                        self, node,
                        f"{hit} while holding "
                        f"{sorted('self.' + h for h in held)}; blocking "
                        "under a lock stalls every other thread on it")
                else:
                    # one-level call-following (ISSUE 7): a same-class
                    # helper's NOT-under-its-own-lock blocking calls run
                    # under everything held here. Skip helpers whose own
                    # base locks are non-empty — their bodies report
                    # directly (caller-held inference), and one finding
                    # per defect is the contract.
                    m = _self_attr_name(node.func)
                    helper = cm.methods.get(m) if m else None
                    if helper is not None \
                            and not cm._method_locks(m, helper):
                        inner = self._helper_blocking(mod, cm, helper)
                        if inner:
                            yield mod.finding(
                                self, node,
                                f"'self.{m}()' makes a blocking call "
                                f"({inner}) and is called here while "
                                f"holding "
                                f"{sorted('self.' + h for h in held)}; "
                                "blocking under a lock stalls every "
                                "other thread on it")
            for child in ast.iter_child_nodes(node):
                yield from visit(child, held)

        for stmt in fn.body:
            yield from visit(stmt, base)

    def _helper_blocking(self, mod: Module, cm: _ClassModel,
                         helper: ast.AST) -> Optional[str]:
        """First blocking call a helper makes at its top level (not under
        a ``with`` of its own — those release points are the helper's own
        business)."""
        def scan(node: ast.AST) -> Optional[str]:
            if isinstance(node, (ast.With, ast.FunctionDef,
                                 ast.AsyncFunctionDef, ast.Lambda)):
                return None
            if isinstance(node, ast.Call):
                hit = self._blocking(mod, cm, node)
                if hit:
                    return hit
            for child in ast.iter_child_nodes(node):
                hit = scan(child)
                if hit:
                    return hit
            return None

        for stmt in helper.body:
            hit = scan(stmt)
            if hit:
                return hit
        return None

    @staticmethod
    def _blocking(mod: Module, cm: _ClassModel,
                  node: ast.Call) -> Optional[str]:
        qn = mod.qualname(node.func)
        if qn in _BLOCKING_CALLS:
            return f"'{qn}'"
        if isinstance(node.func, ast.Attribute):
            meth = node.func.attr
            recv = _self_attr_name(node.func.value)
            if meth == "join" and recv is not None \
                    and ("thread" in recv.lower() or "proc" in recv.lower()):
                return f"'self.{recv}.join()'"
            if meth == "wait" and recv is not None \
                    and recv in cm.exempt_attrs \
                    and recv not in cm.cond_to_lock \
                    and recv not in cm.lock_attrs:
                # Event/Semaphore wait (Condition.wait releases the lock
                # and lives in lock_attrs, so it never reaches here)
                return f"'self.{recv}.wait()'"
        return None


@register
class SwallowedException(Rule):
    id = "C303"
    name = "swallowed-exception"
    doc = ("bare/broad except whose body neither re-raises nor calls "
           "anything (no logging, no status update)")

    _BROAD = {"Exception", "BaseException"}

    def check(self, mod: Module) -> Iterable[Finding]:
        for node in mod.walk():
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._is_broad(mod, node):
                continue
            has_raise = any(isinstance(n, ast.Raise)
                            for n in ast.walk(node))
            has_call = any(isinstance(n, ast.Call)
                           for n in ast.walk(node))
            if has_raise or has_call:
                continue
            label = "bare 'except:'" if node.type is None else \
                f"'except {ast.unparse(node.type)}:'"
            yield mod.finding(
                self, node,
                f"{label} silently swallows the error (no re-raise, no "
                "log, no status update); narrow it or log before "
                "continuing")

    def _is_broad(self, mod: Module, node: ast.ExceptHandler) -> bool:
        if node.type is None:
            return True
        types = node.type.elts if isinstance(node.type, ast.Tuple) \
            else [node.type]
        for t in types:
            qn = mod.qualname(t) or ""
            if qn.split(".")[-1] in self._BROAD:
                return True
        return False
