"""Family T — distributed-liveness rules (ISSUE 20 tentpole).

PRs 16-18 made the platform genuinely distributed — cross-host KV
handoff with hand-tuned connect/ack budgets, a background ``kv-migrate``
thread, scrape threads, dispatcher threads — and the recurring chaos bug
class is always the same: an unbounded blocking call or an orphaned
background thread wedges a replica. These rules enforce statically the
liveness discipline PR 17 applied by hand to exactly one path; the
runtime half is ``KFTPU_SANITIZE=threads`` (runtime/sanitize.py), which
stamps every thread with its creation site and asserts quiescence at
engine/server/router stop.

- T801 ``unbounded-blocking-call``: socket/HTTP (``urlopen``,
  ``http.client``, ``socket.create_connection``), ``Queue.get``,
  ``Condition``/``Event``/``Popen.wait``, ``subprocess.*`` and
  ``Thread.join`` in production code with no timeout/deadline argument.
  Wrapper-aware one level: a call into a local/imported def that takes a
  ``timeout``/``deadline`` parameter defaulting to None and threads it
  into a blocking call must pass that argument.
  ``# blocking-ok: <reason>`` closes a deliberate site.
- T802 ``ad-hoc-retry-loop``: a loop whose body sleeps
  (``time.sleep``) and swallows-and-retries an exception around a call,
  without going through ``serve/retry.py::call_with_retry`` — the
  blessed helper with jittered backoff and a bounded attempt budget.
- T803 ``leaked-thread``: a ``threading.Thread`` stored on ``self`` in
  a class whose stop/close/shutdown surface never joins it (plus the
  function-local variant via the shared ``core.leaky_allocs`` pairing
  primitive — a non-daemon local thread that no path joins).
- T804 ``thread-lifecycle``: (a) a non-daemon background thread created
  in a class with no stop/close/shutdown surface at all — nothing can
  ever reap it; (b) an UNBOUNDED (T801-class) blocking call made while
  a lock is held — tightening C302 with the timeout fact for the
  attr-based waits (queue gets, generic ``.wait()``/``.join()``) C302's
  fixed call set misses. Held-lock sites report here or as C302, never
  also as T801 (one finding per defect).
- T805 ``deadline-propagation-drift``: a scope (handler class or
  function) that reads the ``X-Kftpu-Deadline-Ms`` header — resolved
  through the X-family header extraction, cross-module via the Program —
  but issues a downstream network call with a FIXED literal timeout
  instead of a budget derived from the deadline (a missing timeout is
  T801's finding; a constant one is drift).

All T-rules skip test files and honor ``# blocking-ok: <reason>``.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from kubeflow_tpu.analysis.core import (
    Finding, Module, Rule, leaky_allocs, register,
)
from kubeflow_tpu.analysis.rules_concurrency import (
    _ClassModel, _self_attr_name, class_models,
)
from kubeflow_tpu.analysis.rules_concurrency import (
    BlockingCallUnderLock as _C302,
)
from kubeflow_tpu.analysis.rules_contracts import _extract, _resolve_pending
from kubeflow_tpu.analysis.rules_resources import _attr_chain, _is_test_path

# Argument spellings that count as a bound (this codebase's vocabulary).
_TIMEOUT_KWARGS = {
    "timeout", "timeout_s", "timeout_ms", "deadline", "deadline_s",
    "deadline_ms", "budget", "budget_s", "grace_s",
}
# Direct primitives: qualname -> positional index of the timeout arg
# (None = keyword-only in practice).
_NET_POS: dict[str, Optional[int]] = {
    "urllib.request.urlopen": 2,
    "socket.create_connection": 1,
    "http.client.HTTPConnection": 2,
    "http.client.HTTPSConnection": 2,
    "requests.get": None,
    "requests.post": None,
    "requests.request": None,
}
_SUBPROC = {
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output",
}
_THREAD_TYPES = {"threading.Thread", "threading.Timer"}
_STOP_SURFACE = {
    "stop", "close", "shutdown", "terminate", "join", "quit",
    "__exit__", "__del__",
}


def _bounded(call: ast.Call, pos_idx: Optional[int] = None) -> bool:
    """The call carries a timeout/deadline argument (an explicit
    ``timeout=None`` does NOT count; a ``**kwargs`` splat does — we
    cannot see inside it and presuming a bound never invents a
    finding)."""
    for kw in call.keywords:
        if kw.arg is None:
            return True
        if kw.arg in _TIMEOUT_KWARGS:
            return not (isinstance(kw.value, ast.Constant)
                        and kw.value.value is None)
    if pos_idx is not None and len(call.args) > pos_idx:
        return True
    return False


def _queueish(recv: str) -> bool:
    last = recv.split(".")[-1].lower()
    return "queue" in last or last == "q" or last.endswith("_q")


def _unbounded_blocking(mod: Module, call: ast.Call) -> Optional[str]:
    """Description of why this call can block forever, or None."""
    qn = mod.qualname(call.func)
    if qn in _NET_POS:
        if not _bounded(call, _NET_POS[qn]):
            return f"'{qn}(...)' with no timeout"
        return None
    if qn in _SUBPROC:
        if not _bounded(call):
            return f"'{qn}(...)' with no timeout"
        return None
    if not isinstance(call.func, ast.Attribute):
        return None
    meth = call.func.attr
    recv = _attr_chain(call.func.value)
    if meth == "join" and not call.args and not _bounded(call):
        # str.join / os.path.join always take an argument, so a zero-arg
        # join is a thread/process/pool join.
        return f"'{recv or '...'}.join()' with no timeout"
    if meth == "wait" and not call.args and not _bounded(call):
        # Event/Condition/Popen/grpc-event wait; a bounded wait passes
        # the timeout positionally (first arg) or by keyword.
        return f"'{recv or '...'}.wait()' with no timeout"
    if meth == "communicate" and not call.args and not _bounded(call):
        return f"'{recv or '...'}.communicate()' with no timeout"
    if meth == "get" and _queueish(recv) and not call.args \
            and not _bounded(call) and not _nonblocking(call):
        return f"'{recv}.get()' with no timeout"
    if meth == "put" and _queueish(recv) and not _bounded(call) \
            and any(kw.arg == "block" and isinstance(kw.value, ast.Constant)
                    and kw.value.value is True for kw in call.keywords):
        # put blocks only on a bounded queue; an explicit block=True is
        # the author saying this one is.
        return f"'{recv}.put(..., block=True)' with no timeout"
    return None


def _nonblocking(call: ast.Call) -> bool:
    return any(kw.arg == "block" and isinstance(kw.value, ast.Constant)
               and kw.value.value is False for kw in call.keywords)


_BLOCKING_ATTRS = {"wait", "join", "get", "communicate", "put"}


def _param_flows_to_blocking(mod: Module, target: ast.AST,
                             tparam: str) -> bool:
    """Some call inside ``target`` passes the ``tparam`` Name (as an arg
    or keyword) to a known blocking primitive."""
    for n in ast.walk(target):
        if not (isinstance(n, ast.Name) and n.id == tparam):
            continue
        cur = getattr(n, "_parent", None)
        if isinstance(cur, ast.keyword):
            cur = getattr(cur, "_parent", None)
        if not isinstance(cur, ast.Call):
            continue
        qn = mod.qualname(cur.func)
        if qn in _NET_POS or qn in _SUBPROC:
            return True
        if isinstance(cur.func, ast.Attribute) \
                and cur.func.attr in _BLOCKING_ATTRS:
            return True
    return False


def _wrapper_unbounded(mod: Module, call: ast.Call,
                       fn: Optional[ast.AST]) -> Optional[str]:
    """One-level wrapper resolution: the call targets a def that takes a
    timeout-ish parameter defaulting to None and threads it into some
    call in its body — the call site must pass that argument (a non-None
    default means the wrapper is bounded by default)."""
    if _bounded(call):
        return None
    target: Optional[ast.AST] = None
    tmod = mod
    if mod.program is not None and fn is not None:
        got = mod.program.resolve_call(mod, call, fn)
        if got is not None:
            tmod, target = got
    elif fn is not None:
        target = mod.callgraph.resolve_call(call, fn)
    if not isinstance(target, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    params = list(target.args.posonlyargs) + list(target.args.args)
    names = [p.arg for p in params] + [p.arg for p in target.args.kwonlyargs]
    tparam = next((n for n in names if n in _TIMEOUT_KWARGS), None)
    if tparam is None:
        return None
    # default value of the timeout parameter
    defaults = dict(zip([p.arg for p in params[len(params)
                                               - len(target.args.defaults):]],
                        target.args.defaults))
    defaults.update({p.arg: d for p, d in zip(target.args.kwonlyargs,
                                              target.args.kw_defaults)
                     if d is not None})
    dflt = defaults.get(tparam)
    if dflt is not None and not (isinstance(dflt, ast.Constant)
                                 and dflt.value is None):
        return None         # bounded by default
    # The wrapper must thread the budget into an actual BLOCKING
    # primitive ('urlopen(url, timeout=timeout)') — forwarding it into a
    # dataclass / another wrapper ('Request(deadline=deadline)') is
    # plumbing, not a wait this call site could wedge on.
    if not _param_flows_to_blocking(tmod, target, tparam):
        return None
    # A wrapper that BRANCHES on `param is None` has designed "None =
    # don't block / no deadline" semantics (controller's non-blocking
    # event drain, submit's optional request deadline) — the default is
    # a choice, not an oversight.
    for n in ast.walk(target):
        if isinstance(n, ast.Compare) and isinstance(n.left, ast.Name) \
                and n.left.id == tparam \
                and any(isinstance(op, (ast.Is, ast.IsNot))
                        for op in n.ops) \
                and any(isinstance(c, ast.Constant) and c.value is None
                        for c in n.comparators):
            return None
    # positional pass? (offset 1 when the target is a bound method)
    idx = next((i for i, p in enumerate(params) if p.arg == tparam), None)
    if idx is not None:
        off = 1 if params and params[0].arg in ("self", "cls") \
            and isinstance(call.func, ast.Attribute) else 0
        if len(call.args) > idx - off:
            return None
    return (f"call to '{target.name}(...)' without its '{tparam}' "
            "argument (defaults to unbounded)")


def _lock_held_calls(mod: Module) -> dict[int, tuple[frozenset,
                                                     "_ClassModel"]]:
    """id(call) -> (held locks, class model) for every call made while a
    class lock is lexically held — the C302 traversal, shared by
    T801 (skip: the sharper under-lock rules own those sites) and
    T804(b). Memoized on the module."""
    def build(m: Module) -> dict:
        out: dict[int, tuple[frozenset, _ClassModel]] = {}

        def visit(cm: _ClassModel, node: ast.AST, held: frozenset) -> None:
            if isinstance(node, ast.With):
                extra = set()
                for item in node.items:
                    a = _self_attr_name(item.context_expr)
                    if a and a in cm.lock_attrs:
                        extra.add(cm._canonical_lock(a))
                inner = frozenset(held | extra)
                for child in node.body:
                    visit(cm, child, inner)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return
            if held and isinstance(node, ast.Call):
                out[id(node)] = (held, cm)
            for child in ast.iter_child_nodes(node):
                visit(cm, child, held)

        for cm in class_models(m):
            if not cm.lock_attrs:
                continue
            for name, fn in cm.methods.items():
                base = cm._method_locks(name, fn)
                for stmt in fn.body:
                    visit(cm, stmt, base)
        return out

    return mod.memo("t_lock_held_calls", build)


def _blocking_ok(mod: Module, node: ast.AST) -> bool:
    return mod.annotation(node, "blocking_ok") is not None


@register
class UnboundedBlockingCall(Rule):
    id = "T801"
    name = "unbounded-blocking-call"
    doc = ("network / queue / wait / join / subprocess call with no "
           "timeout or deadline — one wedged peer stalls this component "
           "forever; pass a bound or annotate '# blocking-ok: <reason>'")

    def check(self, mod: Module) -> Iterable[Finding]:
        if _is_test_path(mod.relpath):
            return
        held = _lock_held_calls(mod)
        for call in mod.walk(ast.Call):
            if id(call) in held:
                continue        # C302 / T804(b) own held-lock sites
            desc = _unbounded_blocking(mod, call)
            if desc is None:
                fn = mod.enclosing_function(call)
                desc = _wrapper_unbounded(mod, call, fn)
            if desc is None or _blocking_ok(mod, call):
                continue
            yield mod.finding(
                self, call,
                f"unbounded blocking call: {desc}; a wedged peer stalls "
                "this component forever — pass a timeout/deadline or "
                "annotate '# blocking-ok: <reason>'")


@register
class AdHocRetryLoop(Rule):
    id = "T802"
    name = "ad-hoc-retry-loop"
    doc = ("loop body sleeps and swallows-and-retries an exception "
           "without going through serve/retry.py::call_with_retry "
           "(jittered backoff, bounded attempts)")

    def check(self, mod: Module) -> Iterable[Finding]:
        if _is_test_path(mod.relpath):
            return
        if mod.relpath.replace("\\", "/").endswith("serve/retry.py"):
            return              # the blessed helper itself
        for loop in mod.walk(ast.While, ast.For):
            if mod.line_annotation(loop.lineno, "blocking_ok") is not None \
                    or mod.line_annotation(loop.lineno - 1, "blocking_ok") \
                    is not None:
                continue
            sleeps = blessed = False
            retried: Optional[ast.Try] = None
            for node in ast.walk(loop):
                if not isinstance(node, (ast.Call, ast.Try)):
                    continue
                if isinstance(node, ast.Try):
                    if retried is None and self._retries(node):
                        retried = node
                    continue
                qn = mod.qualname(node.func) or ""
                if qn == "time.sleep":
                    sleeps = True
                elif qn.split(".")[-1] in ("call_with_retry", "RetryPolicy"):
                    blessed = True
            if sleeps and retried is not None and not blessed:
                yield mod.finding(
                    self, loop,
                    "ad-hoc retry loop (time.sleep + swallow-and-retry "
                    f"except at line {retried.lineno}); use "
                    "serve/retry.py::call_with_retry — jittered backoff, "
                    "bounded attempts, injectable sleep")

    @staticmethod
    def _retries(node: ast.Try) -> bool:
        """A handler execution can fall through (reach the next loop
        iteration) and the guarded body actually calls something."""
        if not node.handlers:
            return False
        if not any(isinstance(n, ast.Call)
                   for stmt in node.body for n in ast.walk(stmt)):
            return False
        for h in node.handlers:
            if not h.body:
                return True
            last = h.body[-1]
            if not isinstance(last, (ast.Raise, ast.Return, ast.Break)):
                return True
        return False


@register
class LeakedThread(Rule):
    id = "T803"
    name = "leaked-thread"
    doc = ("threading.Thread stored on self in a class whose "
           "stop/close/shutdown surface never joins it, or a non-daemon "
           "local thread no path joins (core.leaky_allocs pairing)")

    def check(self, mod: Module) -> Iterable[Finding]:
        if _is_test_path(mod.relpath):
            return
        yield from self._class_threads(mod)
        yield from self._local_threads(mod)

    # -- self.X = threading.Thread(...) -----------------------------------

    def _class_threads(self, mod: Module) -> Iterable[Finding]:
        for cm in class_models(mod):
            sites: dict[str, ast.Call] = {}
            joined: set[str] = set()
            for fn in cm.methods.values():
                for node in ast.walk(fn):
                    if isinstance(node, ast.Assign) \
                            and isinstance(node.value, ast.Call) \
                            and mod.qualname(node.value.func) \
                            in _THREAD_TYPES:
                        for t in node.targets:
                            attr = _self_attr_name(t)
                            if attr:
                                sites.setdefault(attr, node.value)
                    elif isinstance(node, ast.Call) \
                            and isinstance(node.func, ast.Attribute) \
                            and node.func.attr == "join":
                        attr = _self_attr_name(node.func.value)
                        if attr:
                            joined.add(attr)
            if not sites:
                continue
            stop_methods = sorted(set(cm.methods) & _STOP_SURFACE)
            if not stop_methods:
                continue        # no stop surface at all: T804's finding
            for attr, site in sorted(sites.items()):
                if attr in joined or _blocking_ok(mod, site):
                    continue
                yield mod.finding(
                    self, site,
                    f"'{cm.cls.name}.{attr}' is a background thread but "
                    f"the stop surface ({', '.join(stop_methods)}) never "
                    f"joins it — the thread outlives the component; join "
                    "it (with a timeout) in stop/close",
                    symbol=f"{cm.cls.name}.{attr}")

    # -- t = threading.Thread(...) in a function ---------------------------

    def _local_threads(self, mod: Module) -> Iterable[Finding]:
        def is_thread(call: ast.Call) -> bool:
            if mod.qualname(call.func) not in _THREAD_TYPES:
                return False
            return not any(
                kw.arg == "daemon" and isinstance(kw.value, ast.Constant)
                and kw.value.value is True for kw in call.keywords)

        def releases(stmt: ast.stmt, var: str) -> bool:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call):
                    if isinstance(node.func, ast.Attribute) \
                            and node.func.attr in ("join", "append",
                                                   "add", "extend"):
                        tgt = node.func.value if node.func.attr == "join" \
                            else None
                        if isinstance(tgt, ast.Name) and tgt.id == var:
                            return True
                    if any(isinstance(a, ast.Name) and a.id == var
                           for a in node.args):
                        return True
                elif isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, (ast.Subscript, ast.Attribute)):
                            for sub in ast.walk(node.value):
                                if isinstance(sub, ast.Name) \
                                        and sub.id == var:
                                    return True
                elif isinstance(node, ast.Return) and node.value is not None:
                    for sub in ast.walk(node.value):
                        if isinstance(sub, ast.Name) and sub.id == var:
                            return True
            return False

        # Methods of classes with NO stop surface: T804(a) owns every
        # thread ctor there (one finding per defect).
        t804_owned = {
            id(fn) for cm in class_models(mod)
            if not set(cm.methods) & _STOP_SURFACE
            for fn in cm.methods.values()}
        for fn in mod.walk(ast.FunctionDef, ast.AsyncFunctionDef):
            if id(fn) in t804_owned:
                continue
            joins = {
                n.func.value.id for n in ast.walk(fn)
                if isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "join"
                and isinstance(n.func.value, ast.Name)}
            for alloc, var, risky in leaky_allocs(fn, is_thread, releases):
                if var in joins or self._escapes(fn, var) \
                        or _blocking_ok(mod, alloc):
                    continue
                yield mod.finding(
                    self, alloc,
                    f"non-daemon thread '{var}' started in '{fn.name}' "
                    "is never joined on any path — it outlives the "
                    "function; join it (with a timeout) or make it "
                    "daemon")

    @staticmethod
    def _escapes(fn: ast.AST, var: str) -> bool:
        """The thread object leaves the function — returned, stored into
        a container/attribute, or handed to another call — so someone
        else owns the join (the path-sensitive leaky_allocs pairing
        would still flag a risky call BETWEEN ctor and escape, which for
        threads is noise: a failed start() never ran)."""
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                if any(isinstance(a, ast.Name) and a.id == var
                       for a in node.args):
                    return True
                if any(isinstance(kw.value, ast.Name)
                       and kw.value.id == var for kw in node.keywords):
                    return True
            elif isinstance(node, ast.Return) and node.value is not None:
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Name) and sub.id == var:
                        return True
            elif isinstance(node, ast.Assign):
                if any(isinstance(t, (ast.Attribute, ast.Subscript))
                       for t in node.targets):
                    for sub in ast.walk(node.value):
                        if isinstance(sub, ast.Name) and sub.id == var:
                            return True
        return False


@register
class ThreadLifecycle(Rule):
    id = "T804"
    name = "thread-lifecycle"
    doc = ("non-daemon thread in a class with no stop surface (nothing "
           "can ever reap it), or an UNBOUNDED blocking call while a "
           "lock is held (C302 tightened with the timeout fact)")

    def check(self, mod: Module) -> Iterable[Finding]:
        if _is_test_path(mod.relpath):
            return
        yield from self._no_stop_surface(mod)
        yield from self._unbounded_under_lock(mod)

    def _no_stop_surface(self, mod: Module) -> Iterable[Finding]:
        for cm in class_models(mod):
            if set(cm.methods) & _STOP_SURFACE:
                continue
            for fn in cm.methods.values():
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call) \
                            or mod.qualname(node.func) not in _THREAD_TYPES:
                        continue
                    if any(kw.arg == "daemon"
                           and isinstance(kw.value, ast.Constant)
                           and kw.value.value is True
                           for kw in node.keywords):
                        continue
                    if _blocking_ok(mod, node):
                        continue
                    yield mod.finding(
                        self, node,
                        f"non-daemon thread created in "
                        f"'{cm.cls.name}', which has no "
                        "stop/close/shutdown surface — nothing can ever "
                        "reap it; add a stop() that joins, or make it "
                        "daemon with an owned stop event")

    def _unbounded_under_lock(self, mod: Module) -> Iterable[Finding]:
        for call_id, (held, cm) in _lock_held_calls(mod).items():
            call = self._call_by_id(mod, call_id)
            if call is None:
                continue
            if _C302._blocking(mod, cm, call) is not None:
                continue        # C302 reports that site
            desc = _unbounded_blocking(mod, call)
            if desc is None or _blocking_ok(mod, call):
                continue
            yield mod.finding(
                self, call,
                f"unbounded blocking call ({desc}) while holding "
                f"{sorted('self.' + h for h in held)} — every thread "
                "needing the lock wedges with it; bound the wait or "
                "move it outside the lock")

    @staticmethod
    def _call_by_id(mod: Module, call_id: int) -> Optional[ast.Call]:
        for n in mod.walk(ast.Call):
            if id(n) == call_id:
                return n
        return None


@register
class DeadlinePropagationDrift(Rule):
    id = "T805"
    name = "deadline-propagation-drift"
    doc = ("scope reads the X-Kftpu-Deadline-Ms header but issues a "
           "downstream network call with a FIXED literal timeout — the "
           "caller's budget is ignored; derive the bound from the "
           "deadline (serve/router.py::_budget_s)")

    _PREFIX = "x-kftpu-deadline"

    def check(self, mod: Module) -> Iterable[Finding]:
        if _is_test_path(mod.relpath):
            return
        ex = _extract(mod)
        reads = [n for v, n in ex["headers_read"]
                 if v.lower().startswith(self._PREFIX)]
        for qual, direction, node in ex["headers_pending"]:
            if direction != "read":
                continue
            val = _resolve_pending(mod.program, qual)
            if val is not None and val.lower().startswith(self._PREFIX):
                reads.append(node)
        if not reads:
            return
        scopes: list[ast.AST] = []
        for n in reads:
            scope = self._scope_of(mod, n)
            if scope is not None and scope not in scopes:
                scopes.append(scope)
        seen: set[int] = set()
        for scope in scopes:
            label = getattr(scope, "name", "<module>")
            for call in ast.walk(scope):
                if not isinstance(call, ast.Call) or id(call) in seen:
                    continue
                seen.add(id(call))
                qn = mod.qualname(call.func)
                if qn not in _NET_POS:
                    continue
                fixed = self._fixed_timeout(call, _NET_POS[qn])
                if fixed is None or _blocking_ok(mod, call):
                    continue
                yield mod.finding(
                    self, call,
                    f"'{label}' reads the deadline header but calls "
                    f"'{qn}' with a fixed timeout={fixed} — the "
                    "caller's budget is ignored; derive the bound from "
                    "the deadline (see serve/router.py::_budget_s)")

    @staticmethod
    def _scope_of(mod: Module, node: ast.AST) -> Optional[ast.AST]:
        cur = getattr(node, "_parent", None)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur
            cur = getattr(cur, "_parent", None)
        return mod.enclosing_function(node)

    @staticmethod
    def _fixed_timeout(call: ast.Call,
                       pos_idx: Optional[int]) -> Optional[object]:
        """The literal constant bound this call passes, or None when the
        bound is missing (T801's finding) or derived (an expression)."""
        for kw in call.keywords:
            if kw.arg in _TIMEOUT_KWARGS \
                    and isinstance(kw.value, ast.Constant) \
                    and kw.value.value is not None:
                return kw.value.value
        if pos_idx is not None and len(call.args) > pos_idx \
                and isinstance(call.args[pos_idx], ast.Constant):
            return call.args[pos_idx].value
        return None
