"""``kftpu lint`` — codebase-aware static analysis for the platform.

The last three PRs each burned a debugging session on a defect class a
machine can catch from the AST alone: an implicit per-round host→device
upload hiding in the decode hot loop (the PR-4 ``jnp.asarray(self._table)``
bug), control-plane state mutated cross-thread without its lock (chaos
tests catch that only probabilistically), and metric-name hygiene enforced
only at render time. This package is the machine: an AST walker, a rule
registry, and two rule families tuned to how THIS codebase is written —
device hygiene over the serving/ops/parallel hot paths, lock discipline
over the threaded control plane — plus the metric-name rules ported from
``obs/registry.lint()`` to definition sites.

Annotation grammar (comments; same line as the construct or the line
directly above):

- ``# guarded_by: <lock_attr>`` — on an attribute's ``__init__``
  assignment: every mutation of the attribute outside ``__init__`` must
  hold ``self.<lock_attr>`` (lexically under ``with self.<lock_attr>`` /
  a Condition built from it, or in a method that declares the lock held).
- ``# lockfree: <reason>`` — on an attribute's ``__init__`` assignment:
  deliberately unsynchronized (thread-confined, delegated, GIL-atomic);
  the reason is required and shows up in ``--list-annotations`` audits.
- ``# requires_lock: <lock_attr>`` — on a ``def``: callers hold the lock;
  the body counts as guarded. Methods named ``*_locked`` get this
  implicitly (the codebase's existing convention).
- ``# hot-loop`` — on a ``def``: the function is on the decode/dispatch
  hot path; blocking host syncs and full-buffer uploads are findings.
- ``# traced`` — on a ``def``: the body is compiled under ``jax.jit``
  (used where the jit wrapping happens in another module); host syncs
  inside are findings.
- ``# sync-point: <reason>`` — on a line inside a hot-loop function: this
  host sync is the designed one (e.g. the pipelined consume fetch).
- ``# mesh-context: <reason>`` — on a ``def``: the function runs under a
  mesh / ``shard_map`` context established by a caller this module cannot
  see; collectives with literal axis names inside are bound there (S405).
- ``# retrace-ok: <reason>`` — on a line inside a function: this jitted
  call site's dispatch-signature instability is intentional (a cold path
  where the retrace is cheaper than padding); closes the F6xx
  compilation-stability rules on that line.
- ``# contract: <reason>`` — on a name-exchange site (metric series
  reference, header set/read, ``KFTPU_*`` env access, status-field
  read): this name is INTENTIONALLY one-sided — a user-facing knob
  nothing in the tree sets, a value exported for code outside the lint
  scan — and the X7xx cross-component contract rules accept it with the
  stated reason on record.
- ``# blocking-ok: <reason>`` — on a blocking call site (or the line
  above): this call is DELIBERATELY unbounded — a fault injector's
  wedge, a final reap after terminate, a durability wait whose caller
  owns the deadline — and the T8xx liveness rules accept it with the
  stated reason on record.
- ``# lint: disable=D101[,C301...]`` — suppress specific rules on this
  line.

Interprocedural core (ISSUE 7): every module gets a call graph with
ONE-level call-following (``Module.callgraph``) so dataflow rules — jit
region scanning, donation tracking, lock-held regions, resource pairing —
see through same-module helper calls without whole-program analysis, plus
a shared resource-pairing primitive (``leaky_allocs``) for the
alloc/free-on-exception-path rule family.

Whole-program core (ISSUE 8): one ``Program`` per lint run parses every
``kubeflow_tpu/*`` module exactly ONCE (a process-level AST cache shares
parses across rule families, seeded-regression re-lints, and ``--changed``
subsets that still need package-wide resolution context), resolves
imports across modules (``from kubeflow_tpu.serve.spec_decode import
verify_step`` makes the callee's def visible to a rule scanning the
importer), and propagates jit/donation/static-argnum facts transitively
through the cross-module call graph with a depth bound
(``Program.transitive_callees``). The compilation-stability family
(``rules_compile.py``, F6xx) is built on this: a dispatch-signature fact
attached to a jitted callable in one module follows it to call sites in
every other.

Baseline: a checked-in JSON file (default ``.kftpu-lint-baseline.json``,
discovered upward from the scanned paths) holding fingerprints of known
pre-existing findings with a one-line justification each, so legacy debt
does not block CI while new findings still fail it. Fingerprints are
line-number-free (rule | path | enclosing symbol | message), so unrelated
edits don't invalidate the baseline.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import io
import json
import os
import re
import sys
import time
import tokenize
from collections import Counter
from typing import Iterable, Optional

# -- findings ------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str          # e.g. "D103"
    name: str          # e.g. "full-buffer-reupload"
    path: str          # repo-relative, '/'-separated
    line: int
    col: int
    message: str
    symbol: str = ""   # enclosing Class.method qualname (baseline key part)

    @property
    def fingerprint(self) -> str:
        # Deliberately line-free: the baseline must survive unrelated edits.
        return f"{self.rule}|{self.path}|{self.symbol}|{self.message}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"[{self.name}] {self.message}")

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


# -- annotations ---------------------------------------------------------------

_ANNOT_RES = {
    "guarded_by": re.compile(r"#\s*guarded_by:\s*([A-Za-z_]\w*)"),
    "lockfree": re.compile(r"#\s*lockfree:\s*(\S.*)"),
    "requires_lock": re.compile(r"#\s*requires_lock:\s*([A-Za-z_]\w*)"),
    "hot_loop": re.compile(r"#\s*hot-loop\b"),
    "traced": re.compile(r"#\s*traced\b"),
    "sync_point": re.compile(r"#\s*sync-point:\s*(\S.*)"),
    "mesh_context": re.compile(r"#\s*mesh-context:\s*(\S.*)"),
    "retrace_ok": re.compile(r"#\s*retrace-ok:\s*(\S.*)"),
    "contract": re.compile(r"#\s*contract:\s*(\S.*)"),
    "blocking_ok": re.compile(r"#\s*blocking-ok:\s*(\S.*)"),
}
_DISABLE_RE = re.compile(r"#\s*lint:\s*disable=([A-Z]+\d+(?:\s*,\s*[A-Z]+\d+)*)")


class Module:
    """One parsed source file: AST with parent links, comment map, import
    aliases, and the annotation lookups every rule shares."""

    def __init__(self, relpath: str, text: str):
        self.relpath = relpath.replace(os.sep, "/")
        self.text = text
        self.tree = ast.parse(text)
        # One walk serves both the parent links and the cached node list
        # (``Module.walk``): every whole-tree scan a rule family does
        # afterwards iterates this list instead of re-walking the tree.
        self._nodes: list[ast.AST] = [self.tree]
        for node in self._nodes:        # grows while iterating: BFS
            for child in ast.iter_child_nodes(node):
                child._parent = node  # type: ignore[attr-defined]
                self._nodes.append(child)
        self.comments: dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(io.StringIO(text).readline):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string
        except tokenize.TokenError:
            pass
        self.aliases = self._build_aliases()
        self._callgraph: Optional["CallGraph"] = None
        # Set by Program when this module is linted in a whole-program
        # run; None for standalone lint_source fixtures (rules degrade to
        # module-local analysis).
        self.program: Optional["Program"] = None
        self._memo: dict = {}

    def memo(self, key: str, build):
        """Per-module computed-structure cache (class models, hot-loop
        lists, jit tables): each is derived from the immutable tree, so
        rule families share ONE computation per module instead of
        re-deriving it per rule — the parse-once contract extended to
        everything parsed FROM the parse."""
        if key not in self._memo:
            self._memo[key] = build(self)
        return self._memo[key]

    def walk(self, *types: type) -> Iterable[ast.AST]:
        """Whole-tree node iteration off the cached list built at parse
        (``ast.walk(mod.tree)`` re-walks the tree per call — at ~30
        whole-tree scans per module across the rule families that was
        the self-scan's single biggest cost). ``types`` filters by
        isinstance."""
        if not types:
            return iter(self._nodes)
        return (n for n in self._nodes if isinstance(n, types))

    @property
    def callgraph(self) -> "CallGraph":
        if self._callgraph is None:
            self._callgraph = CallGraph(self)
        return self._callgraph

    # -- imports / names ---------------------------------------------------

    def _build_aliases(self) -> dict[str, str]:
        aliases: dict[str, str] = {}
        for node in self._nodes:
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        aliases[a.asname] = a.name
                    else:
                        # ``import urllib.request`` binds the TOP package
                        # name only; the attribute chain already spells
                        # the rest (mapping urllib -> urllib.request
                        # would double the segment:
                        # urllib.request.request.urlopen).
                        top = a.name.split(".")[0]
                        aliases.setdefault(top, top)
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    aliases[a.asname or a.name] = f"{node.module}.{a.name}"
        return aliases

    def qualname(self, node: ast.AST) -> Optional[str]:
        """Dotted, alias-expanded name of a Name/Attribute chain
        (``np.asarray`` → ``numpy.asarray``), or None for anything
        dynamic."""
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            base = self.aliases.get(node.id, node.id)
            return ".".join([base] + list(reversed(parts)))
        return None

    # -- annotations -------------------------------------------------------

    def _lines_for(self, node: ast.AST) -> Iterable[int]:
        line = getattr(node, "lineno", None)
        if line is None:
            return ()
        end = getattr(node, "end_lineno", line) or line
        return range(line - 1, end + 1)

    def annotation(self, node: ast.AST, name: str) -> Optional[str]:
        """Value of annotation ``name`` attached to ``node`` (its line
        span or the line directly above), else None. Marker annotations
        (hot-loop/traced) return "" when present."""
        regex = _ANNOT_RES[name]
        for ln in self._lines_for(node):
            m = regex.search(self.comments.get(ln, ""))
            if m:
                return m.group(1).strip() if m.groups() else ""
        return None

    def line_annotation(self, line: int, name: str) -> Optional[str]:
        m = _ANNOT_RES[name].search(self.comments.get(line, ""))
        if m:
            return m.group(1).strip() if m.groups() else ""
        return None

    def suppressed(self, line: int, rule: str) -> bool:
        for ln in (line, line - 1):
            m = _DISABLE_RE.search(self.comments.get(ln, ""))
            if m and rule in {r.strip() for r in m.group(1).split(",")}:
                return True
        return False

    # -- structure ---------------------------------------------------------

    def symbol_for(self, node: ast.AST) -> str:
        parts: list[str] = []
        cur = getattr(node, "_parent", None)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            parts.append(node.name)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                parts.append(cur.name)
            cur = getattr(cur, "_parent", None)
        return ".".join(reversed(parts))

    def enclosing_function(self, node: ast.AST):
        cur = getattr(node, "_parent", None)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return cur
            cur = getattr(cur, "_parent", None)
        return None

    def finding(self, rule: "Rule", node: ast.AST, message: str,
                symbol: Optional[str] = None) -> Finding:
        return Finding(rule=rule.id, name=rule.name, path=self.relpath,
                       line=getattr(node, "lineno", 0),
                       col=getattr(node, "col_offset", 0) + 1,
                       message=message,
                       symbol=symbol if symbol is not None
                       else self.symbol_for(node))


# -- interprocedural core ------------------------------------------------------


_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


class CallGraph:
    """Module-level call graph with ONE-level call-following.

    Resolution is deliberately modest — exactly what same-module helper
    calls need and no more: a bare ``name(...)`` resolves to a
    module-level ``def name``; ``self.m(...)`` inside a method resolves to
    that class's method ``m``. Anything dynamic stays unresolved. Rules use
    ``callees`` to peek one level into helpers (donation reads, jit-region
    host syncs, lock acquisitions) and ``callers`` to stay conservative
    (skip a helper that is also reachable from a context the rule does not
    model)."""

    def __init__(self, mod: Module):
        self.mod = mod
        self.module_fns: dict[str, ast.AST] = {}
        self.class_methods: dict[str, dict[str, ast.AST]] = {}
        # attr name -> same-module class name, from `self.X = Cls(...)`
        self.attr_class: dict[tuple[str, str], str] = {}
        for stmt in mod.tree.body:
            if isinstance(stmt, _FUNC_NODES):
                self.module_fns[stmt.name] = stmt
            elif isinstance(stmt, ast.ClassDef):
                methods = {}
                for s in stmt.body:
                    if isinstance(s, _FUNC_NODES):
                        methods[s.name] = s
                self.class_methods[stmt.name] = methods
        for cname, methods in self.class_methods.items():
            init = methods.get("__init__")
            if init is None:
                continue
            for node in ast.walk(init):
                if not isinstance(node, ast.Assign) or \
                        not isinstance(node.value, ast.Call):
                    continue
                callee = node.value.func
                tgt_cls = callee.id if isinstance(callee, ast.Name) else None
                if tgt_cls not in self.class_methods:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        self.attr_class[(cname, t.attr)] = tgt_cls
        self._callers: Optional[dict[int, set[int]]] = None

    def enclosing_class(self, fn: ast.AST) -> Optional[str]:
        cur = getattr(fn, "_parent", None)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur.name
            cur = getattr(cur, "_parent", None)
        return None

    def resolve_call(self, call: ast.Call, fn: ast.AST) -> Optional[ast.AST]:
        """The same-module FunctionDef a call site targets, or None."""
        func = call.func
        if isinstance(func, ast.Name):
            return self.module_fns.get(func.id)
        if isinstance(func, ast.Attribute) \
                and isinstance(func.value, ast.Name):
            cls = self.enclosing_class(fn)
            if func.value.id == "self" and cls is not None:
                return self.class_methods.get(cls, {}).get(func.attr)
        # self.<attr>.m() where __init__ bound attr to a same-module class
        if isinstance(func, ast.Attribute) \
                and isinstance(func.value, ast.Attribute) \
                and isinstance(func.value.value, ast.Name) \
                and func.value.value.id == "self":
            cls = self.enclosing_class(fn)
            tgt = self.attr_class.get((cls or "", func.value.attr))
            if tgt is not None:
                return self.class_methods.get(tgt, {}).get(func.attr)
        return None

    def callees(self, fn: ast.AST) -> list[ast.AST]:
        out, seen = [], {id(fn)}
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                target = self.resolve_call(node, fn)
                if target is not None and id(target) not in seen:
                    seen.add(id(target))
                    out.append(target)
        return out

    def callers_of(self, fn: ast.AST) -> list[ast.AST]:
        if self._callers is None:
            self._callers = {}
            all_fns = list(self.module_fns.values()) + [
                m for ms in self.class_methods.values()
                for m in ms.values()]
            self._by_id = {id(f): f for f in all_fns}
            for f in all_fns:
                for callee in self.callees(f):
                    self._callers.setdefault(id(callee), set()).add(id(f))
        return [self._by_id[i] for i in self._callers.get(id(fn), ())]


# -- jit facts -----------------------------------------------------------------


_JIT_CTOR_QNS = {"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit"}


@dataclasses.dataclass
class JitFact:
    """What the analyzer knows about one jitted-callable spelling: the
    constructor call, which positional args are static (hashed, not
    traced), and which are donated. The single source every dispatch-
    signature rule (F6xx) and donation rule (D104/S401) reads, so the
    fact set can't drift between families."""

    name: str                       # call-site spelling ('self._decode_n')
    ctor: ast.AST                   # the jax.jit(...) call or decorated def
    static_argnums: tuple[int, ...] = ()
    static_argnames: tuple[str, ...] = ()
    donate_argnums: tuple[int, ...] = ()
    donate_argnames: tuple[str, ...] = ()
    fn_node: Optional[ast.AST] = None   # the wrapped def, when resolvable

    @property
    def donates(self) -> bool:
        return bool(self.donate_argnums or self.donate_argnames)


def _int_tuple(node: Optional[ast.AST]) -> tuple[int, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, int))
    return ()


def _str_tuple(node: Optional[ast.AST]) -> tuple[str, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str))
    return ()


def _fact_from_ctor(mod: Module, name: str, call: ast.Call) -> JitFact:
    fact = JitFact(name=name, ctor=call)
    for kw in call.keywords:
        if kw.arg in ("static_argnums", "donate_argnums"):
            setattr(fact, kw.arg, _int_tuple(kw.value))
        elif kw.arg in ("static_argnames", "donate_argnames"):
            setattr(fact, kw.arg, _str_tuple(kw.value))
    if call.args and isinstance(call.args[0], ast.Name):
        cg = mod.callgraph
        fact.fn_node = cg.module_fns.get(call.args[0].id)
    return fact


def _expr_spelling(node: ast.AST) -> Optional[str]:
    """Dotted source spelling of a Name/Attribute chain (``self._fn``,
    ``engine._decode_n``) — the call-site key jit facts are stored under."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        return ".".join([node.id] + list(reversed(parts)))
    return None


def jit_table(mod: Module) -> dict[str, JitFact]:
    """Every jitted-callable spelling this module defines: ``X = jax.jit
    (...)`` / ``self.X = jax.jit(...)`` assignments anywhere, plus
    ``@jax.jit`` / ``@partial(jax.jit, ...)`` decorated defs (keyed by
    the def's name). Cached on the module."""
    return mod.memo("jit_table", _build_jit_table)


def _build_jit_table(mod: Module) -> dict[str, JitFact]:
    out: dict[str, JitFact] = {}
    for node in mod.walk():
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.value, ast.Call) \
                and mod.qualname(node.value.func) in _JIT_CTOR_QNS:
            name = _expr_spelling(node.targets[0])
            if name:
                out[name] = _fact_from_ctor(mod, name, node.value)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if mod.qualname(dec) in _JIT_CTOR_QNS:
                    out[node.name] = JitFact(name=node.name, ctor=node,
                                             fn_node=node)
                    break
                if isinstance(dec, ast.Call):
                    dqn = mod.qualname(dec.func)
                    if dqn in _JIT_CTOR_QNS or (
                            dqn in ("functools.partial", "partial")
                            and dec.args
                            and mod.qualname(dec.args[0]) in _JIT_CTOR_QNS):
                        fact = _fact_from_ctor(mod, node.name, dec)
                        fact.fn_node = node
                        out[node.name] = fact
                        break
    return out


# -- whole-program core --------------------------------------------------------


def module_dotted_name(relpath: str) -> Optional[str]:
    """``kubeflow_tpu/serve/engine.py`` → ``kubeflow_tpu.serve.engine``;
    ``kubeflow_tpu/__init__.py`` → ``kubeflow_tpu``. None for paths
    outside an importable layout (scripts, bench drivers)."""
    if not relpath.endswith(".py"):
        return None
    parts = relpath[:-3].replace(os.sep, "/").split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    if not parts or not all(p.isidentifier() for p in parts):
        return None
    return ".".join(parts)


# Process-level parse cache: (abspath, mtime_ns, size, relpath) → Module.
# One lint run parses each file once and every rule family shares the
# tree; repeated runs in one process (the seeded-regression self-checks,
# test suites) re-parse only files that actually changed.
_MODULE_CACHE: dict[str, tuple[int, int, str, Module]] = {}


def load_module(path: str, relpath: str) -> Module:
    apath = os.path.abspath(path)
    st = os.stat(apath)
    hit = _MODULE_CACHE.get(apath)
    if hit is not None and hit[:3] == (st.st_mtime_ns, st.st_size, relpath):
        return hit[3]
    with open(apath, encoding="utf-8") as f:
        text = f.read()
    mod = Module(relpath, text)
    _MODULE_CACHE[apath] = (st.st_mtime_ns, st.st_size, relpath, mod)
    return mod


class Program:
    """Whole-program view over one lint run: every module parsed once,
    imports resolved across ``kubeflow_tpu/*``, and jit/donation facts
    followable transitively (depth-bounded) through the cross-module call
    graph. Rules receive it via ``Module.program`` and must degrade to
    module-local analysis when it is None (standalone fixtures)."""

    #: Transitive call-following stops here: deep enough to cross a
    #: dispatch helper chain, shallow enough that one mega-module cannot
    #: make the analysis quadratic.
    MAX_DEPTH = 4

    def __init__(self, modules: Iterable[Module]):
        self.modules: list[Module] = list(modules)
        self.by_path: dict[str, Module] = {}
        self.by_name: dict[str, Module] = {}
        for m in self.modules:
            self.by_path[m.relpath] = m
            dotted = module_dotted_name(m.relpath)
            if dotted is not None:
                self.by_name[dotted] = m
            m.program = self
        self._jit_by_qual: Optional[dict[str, JitFact]] = None
        self._memo: dict = {}

    def memo(self, key: str, build):
        """Per-program computed-structure cache (the X-family contract
        table): whole-program aggregates are derived once per lint run
        and shared by every rule that needs them — the per-module
        ``Module.memo`` contract lifted to the Program."""
        if key not in self._memo:
            self._memo[key] = build(self)
        return self._memo[key]

    # -- name resolution ---------------------------------------------------

    def resolve(self, qualname: str
                ) -> Optional[tuple[Module, ast.AST]]:
        """(module, def/class node) for a fully-dotted name — longest
        module prefix wins, then module-level ``def``/``class`` or one
        ``Class.method`` level."""
        parts = qualname.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            mod = self.by_name.get(".".join(parts[:cut]))
            if mod is None:
                continue
            rest = parts[cut:]
            cg = mod.callgraph
            if len(rest) == 1:
                fn = cg.module_fns.get(rest[0])
                if fn is not None:
                    return mod, fn
                for stmt in mod.tree.body:
                    if isinstance(stmt, ast.ClassDef) \
                            and stmt.name == rest[0]:
                        return mod, stmt
            elif len(rest) == 2:
                m = cg.class_methods.get(rest[0], {}).get(rest[1])
                if m is not None:
                    return mod, m
            return None
        return None

    def resolve_call(self, mod: Module, call: ast.Call, fn: ast.AST
                     ) -> Optional[tuple[Module, ast.AST]]:
        """Cross-module call resolution: same-module first (the ISSUE-7
        callgraph), then the alias-expanded qualname against the program
        (``verify_step(...)`` under ``from ..spec_decode import
        verify_step`` lands on the def in spec_decode.py)."""
        local = mod.callgraph.resolve_call(call, fn)
        if local is not None:
            return mod, local
        qn = mod.qualname(call.func)
        if qn is None:
            return None
        return self.resolve(qn)

    def transitive_callees(self, mod: Module, fn: ast.AST,
                           depth: int = MAX_DEPTH
                           ) -> list[tuple[Module, ast.AST]]:
        """BFS over the cross-module call graph from ``fn``, depth-
        bounded — the propagation primitive jit-region scanning and the
        F6xx fact-following use."""
        out: list[tuple[Module, ast.AST]] = []
        seen = {id(fn)}
        frontier: list[tuple[Module, ast.AST]] = [(mod, fn)]
        for _ in range(max(depth, 0)):
            nxt: list[tuple[Module, ast.AST]] = []
            for cmod, cfn in frontier:
                for node in ast.walk(cfn):
                    if not isinstance(node, ast.Call):
                        continue
                    got = self.resolve_call(cmod, node, cfn)
                    if got is None or id(got[1]) in seen:
                        continue
                    seen.add(id(got[1]))
                    out.append(got)
                    nxt.append(got)
            frontier = nxt
            if not frontier:
                break
        return out

    # -- jit facts ---------------------------------------------------------

    def jit_facts(self, mod: Module) -> dict[str, JitFact]:
        """The jit table visible AT CALL SITES in ``mod``: its own
        definitions plus imported spellings that resolve to jitted
        module-level names elsewhere in the program (``from a import G``
        with ``G = jax.jit(...)`` in a.py makes ``G(...)`` here carry
        a.py's static/donate facts)."""
        out = dict(jit_table(mod))
        if self._jit_by_qual is None:
            self._jit_by_qual = {}
            for m in self.modules:
                dotted = module_dotted_name(m.relpath)
                if dotted is None:
                    continue
                for name, fact in jit_table(m).items():
                    if "." not in name:      # module-level spellings only
                        self._jit_by_qual[f"{dotted}.{name}"] = fact
        for alias, target in mod.aliases.items():
            fact = self._jit_by_qual.get(target)
            if fact is not None and alias not in out:
                out[alias] = fact
        return out


def leaky_allocs(fn: ast.AST, is_alloc, releases_var):
    """Shared resource-pairing dataflow: yield ``(alloc_call, var,
    risky_stmt)`` for every ``var = <alloc>`` whose resource can leak on an
    exception path.

    ``is_alloc(call)`` classifies allocation calls; ``releases_var(stmt,
    var)`` says whether a statement releases/consumes ownership of ``var``
    (stores it into an owning structure, frees it, returns it, or passes it
    on). A statement between the alloc and the consumption that contains
    any call can raise — at which point nothing owns the resource — unless
    the alloc is inside a ``try`` whose handler or ``finally`` releases
    the var."""
    protected: set[int] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Try):
            continue
        cleanup = [s for h in node.handlers for s in h.body]
        cleanup += list(node.finalbody)
        for stmt in node.body:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Call) and is_alloc(sub):
                    var = _alloc_target(sub)
                    if var and any(releases_var(c, var) for c in cleanup):
                        protected.add(id(sub))

    def scan(stmts, cont):
        """``cont`` is the statement continuation after this block (the
        rest of every enclosing block, in execution order) — ownership is
        routinely taken a block boundary later (alloc inside ``try``,
        recorded after it)."""
        for i, stmt in enumerate(stmts):
            if isinstance(stmt, _FUNC_NODES + (ast.ClassDef,)):
                continue
            rest = stmts[i + 1:] + cont
            alloc = None
            if isinstance(stmt, ast.Assign) and \
                    isinstance(stmt.value, ast.Call) and \
                    is_alloc(stmt.value) and id(stmt.value) not in protected:
                alloc = stmt.value
                var = _alloc_target(alloc)
            if alloc is not None and var:
                consumed = False
                for later in rest:
                    if releases_var(later, var):
                        consumed = True
                        break
                    if any(isinstance(n, ast.Call)
                           for n in ast.walk(later)):
                        yield alloc, var, later
                        consumed = True   # reported once; stop tracking
                        break
                if not consumed:
                    yield alloc, var, stmt
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if sub:
                    yield from scan(sub, rest)
            for h in getattr(stmt, "handlers", []) or []:
                yield from scan(h.body, rest)

    yield from scan(list(getattr(fn, "body", [])), [])


def _alloc_target(call: ast.Call) -> Optional[str]:
    stmt = getattr(call, "_parent", None)
    if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
            and isinstance(stmt.targets[0], ast.Name):
        return stmt.targets[0].id
    return None


_CANONICAL_AXES_FALLBACK = (
    "dcn", "pipeline", "data", "fsdp", "expert", "seq", "model",
)
_canonical_axes_cache: Optional[tuple[str, ...]] = None


def canonical_mesh_axes() -> tuple[str, ...]:
    """The platform's canonical mesh-axis names, read from
    ``runtime/mesh.py``'s ``MESH_AXES`` assignment BY AST (the analyzer
    stays import-light: no jax). Falls back to the baked-in tuple when the
    source moves."""
    global _canonical_axes_cache
    if _canonical_axes_cache is not None:
        return _canonical_axes_cache
    axes = _CANONICAL_AXES_FALLBACK
    mesh_py = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "runtime", "mesh.py")
    try:
        with open(mesh_py, encoding="utf-8") as f:
            tree = ast.parse(f.read())
        for stmt in tree.body:
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target] if isinstance(stmt, ast.AnnAssign) else []
            for t in targets:
                if isinstance(t, ast.Name) and t.id == "MESH_AXES" \
                        and isinstance(stmt.value, (ast.Tuple, ast.List)):
                    vals = tuple(e.value for e in stmt.value.elts
                                 if isinstance(e, ast.Constant)
                                 and isinstance(e.value, str))
                    if vals:
                        axes = vals
    except (OSError, SyntaxError, ValueError):
        pass
    _canonical_axes_cache = axes
    return axes


# -- rule registry -------------------------------------------------------------


class Rule:
    id: str = ""
    name: str = ""
    doc: str = ""

    def check(self, mod: Module) -> Iterable[Finding]:  # pragma: no cover
        raise NotImplementedError


_RULES: list[Rule] = []


def register(cls: type) -> type:
    _RULES.append(cls())
    return cls


def all_rules() -> list[Rule]:
    _load_rules()
    return list(_RULES)


_loaded = False


def _load_rules() -> None:
    global _loaded
    if _loaded:
        return
    _loaded = True
    from kubeflow_tpu.analysis import (  # noqa: F401  (registration import)
        rules_compile, rules_concurrency, rules_contracts, rules_device,
        rules_liveness, rules_metrics, rules_resources, rules_sharding,
    )


# -- baseline ------------------------------------------------------------------


class Baseline:
    """Checked-in known-findings file: each entry a line-free fingerprint
    plus a one-line justification. Matching is multiset-aware (the same
    fingerprint may legitimately occur N times)."""

    def __init__(self, entries: Optional[list[dict]] = None,
                 path: Optional[str] = None):
        self.path = path
        self.entries = entries or []

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path) as f:
            doc = json.load(f)
        return cls(doc.get("entries", []), path=path)

    @classmethod
    def from_findings(cls, findings: Iterable[Finding],
                      reason: str = "baselined pre-existing debt"
                      ) -> "Baseline":
        # Sorted at construction AND at save: --update-baseline output is
        # a pure function of the finding SET, so rewriting the baseline
        # from a differently-ordered scan produces a byte-identical file
        # and baseline diffs stay reviewable.
        return cls(sorted(({"fingerprint": f.fingerprint, "reason": reason}
                           for f in findings),
                          key=lambda e: e["fingerprint"]))

    def save(self, path: str) -> None:
        doc = {"version": 1,
               "entries": sorted(self.entries,
                                 key=lambda e: (e["fingerprint"],
                                                e.get("reason", "")))}
        with open(path, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")

    def split(self, findings: list[Finding]
              ) -> tuple[list[Finding], list[Finding]]:
        """(new, baselined)."""
        budget = Counter(e["fingerprint"] for e in self.entries)
        new, matched = [], []
        for f in findings:
            if budget.get(f.fingerprint, 0) > 0:
                budget[f.fingerprint] -= 1
                matched.append(f)
            else:
                new.append(f)
        return new, matched


BASELINE_FILENAME = ".kftpu-lint-baseline.json"


def find_baseline(paths: list[str]) -> Optional[str]:
    """Walk upward from the scanned paths (then the cwd) looking for the
    checked-in baseline file."""
    starts = [os.path.abspath(p) for p in paths] + [os.getcwd()]
    for start in starts:
        cur = start if os.path.isdir(start) else os.path.dirname(start)
        while True:
            cand = os.path.join(cur, BASELINE_FILENAME)
            if os.path.isfile(cand):
                return cand
            parent = os.path.dirname(cur)
            if parent == cur:
                break
            cur = parent
    return None


# -- running -------------------------------------------------------------------


@dataclasses.dataclass
class LintResult:
    new: list[Finding]
    baselined: list[Finding]
    errors: list[Finding]
    files_scanned: int
    wall_time_s: float = 0.0

    @property
    def ok(self) -> bool:
        return not self.new and not self.errors


def iter_py_files(paths: Iterable[str]) -> list[str]:
    out: list[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs
                             if d != "__pycache__" and not d.startswith("."))
            out.extend(os.path.join(root, f) for f in sorted(files)
                       if f.endswith(".py"))
    return out


def lint_module(mod: Module, rules: Optional[list[Rule]] = None
                ) -> list[Finding]:
    """All non-suppressed findings for one parsed module."""
    findings: list[Finding] = []
    for rule in rules if rules is not None else all_rules():
        for f in rule.check(mod):
            if not mod.suppressed(f.line, f.rule):
                findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def lint_source(text: str, relpath: str = "<memory>.py",
                rules: Optional[list[Rule]] = None) -> list[Finding]:
    """Test/embedding entry point: lint one source string."""
    return lint_module(Module(relpath, text), rules=rules)


def lint_sources(sources: dict[str, str],
                 lint: Optional[list[str]] = None,
                 rules: Optional[list[Rule]] = None) -> list[Finding]:
    """Multi-module fixture entry point: parse every source under its
    relpath, wire them into one Program (cross-module resolution works),
    and lint ``lint`` (default: all of them)."""
    mods = {rel: Module(rel, text) for rel, text in sources.items()}
    Program(mods.values())
    findings: list[Finding] = []
    for rel in (lint if lint is not None else sorted(mods)):
        findings.extend(lint_module(mods[rel], rules=rules))
    return findings


class _ParseError(Rule):
    id = "E000"
    name = "parse-error"


_PARSE_ERROR = _ParseError()


def _package_context(root: str) -> list[str]:
    """Files the whole-program resolver should see even when only a
    subset is being linted (the ``--changed`` pre-commit path): the main
    package under ``root`` plus the smoke/bench drivers. The drivers
    matter to the X-family contract rules — they are the in-scan
    CONSUMERS of several metric series and the writers of sanitizer env
    vars, so a changed-file lint without them would misread two-sided
    names as orphans."""
    out: list[str] = []
    pkg = os.path.join(root, "kubeflow_tpu")
    if os.path.isdir(pkg):
        out.extend(iter_py_files([pkg]))
    scripts = os.path.join(root, "scripts")
    if os.path.isdir(scripts):
        out.extend(iter_py_files([scripts]))
    for name in ("bench.py", "bench_serve.py"):
        path = os.path.join(root, name)
        if os.path.isfile(path):
            out.append(path)
    return out


def build_program(paths: list[str], root: Optional[str] = None) -> Program:
    """Parse ``paths`` plus the package-wide resolution context into one
    ``Program`` WITHOUT linting — the entry scripts and tests use to
    reach whole-program tables (the X-family contract extractor, jit
    facts) directly. Unparseable files are skipped; their own lint run
    reports them."""
    root = os.path.abspath(root or os.getcwd())
    mods: list[Module] = []
    seen: set[str] = set()
    for path in iter_py_files(paths) + _package_context(root):
        apath = os.path.abspath(path)
        if apath in seen:
            continue
        seen.add(apath)
        rel = os.path.relpath(apath, root)
        try:
            mods.append(load_module(path, rel))
        except (OSError, SyntaxError, ValueError, UnicodeDecodeError):
            continue
    return Program(mods)


def run_lint(paths: list[str], baseline: Optional[Baseline] = None,
             root: Optional[str] = None) -> LintResult:
    """Lint every .py under ``paths``. Finding paths are relative to
    ``root`` (default: cwd), matching how the baseline was recorded.

    All modules — the linted set plus the package-wide resolution
    context — are parsed once into one ``Program`` shared by every rule
    family; ``wall_time_s`` on the result covers parse + all rules."""
    t0 = time.perf_counter()
    root = os.path.abspath(root or os.getcwd())
    findings: list[Finding] = []
    errors: list[Finding] = []
    files = iter_py_files(paths)
    mods: list[Module] = []
    for path in files:
        rel = os.path.relpath(os.path.abspath(path), root)
        try:
            mods.append(load_module(path, rel))
        except (OSError, SyntaxError, ValueError, UnicodeDecodeError) as exc:
            errors.append(Finding(
                rule="E000", name="parse-error",
                path=rel.replace(os.sep, "/"),
                line=getattr(exc, "lineno", 0) or 0, col=1,
                message=f"cannot parse: {exc}"))
    lint_paths = {m.relpath for m in mods}
    context = list(mods)
    for path in _package_context(root):
        rel = os.path.relpath(os.path.abspath(path), root)
        if rel in lint_paths:
            continue
        try:
            context.append(load_module(path, rel))
        except (OSError, SyntaxError, ValueError, UnicodeDecodeError):
            continue    # context only — its own lint run reports it
    Program(context)
    for mod in mods:
        findings.extend(lint_module(mod))
    if baseline is not None:
        new, matched = baseline.split(findings)
    else:
        new, matched = findings, []
    return LintResult(new=new, baselined=matched, errors=errors,
                      files_scanned=len(files),
                      wall_time_s=time.perf_counter() - t0)


# -- CLI -----------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="kftpu lint",
        description="codebase-aware static analysis (device hygiene + "
                    "lock discipline + sharding/SPMD + resource pairing "
                    "+ metric naming)")
    p.add_argument("paths", nargs="*", default=["kubeflow_tpu"],
                   help="files or directories to scan")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    p.add_argument("--baseline", default=None,
                   help=f"baseline file (default: nearest "
                        f"{BASELINE_FILENAME})")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, ignoring the baseline")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline to the current findings "
                        "(each entry still needs a hand-written reason)")
    p.add_argument("--show-baselined", action="store_true",
                   help="also print findings matched by the baseline")
    p.add_argument("--changed", nargs="?", const="HEAD", default=None,
                   metavar="BASE",
                   help="lint only .py files changed vs BASE (default "
                        "HEAD: the working tree — the fast pre-commit "
                        "path); includes untracked files")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--contracts-json", action="store_true",
                   dest="contracts_json",
                   help="dump the statically-extracted cross-component "
                        "contract table (metric series produced/consumed, "
                        "X-Kftpu-* headers set/read, KFTPU_* env vars, "
                        "status fields) as JSON and exit — the manifest "
                        "the KFTPU_SANITIZE=contract runtime auditor "
                        "diffs against")
    return p


def changed_files(base: str = "HEAD",
                  root: Optional[str] = None) -> list[str]:
    """Paths of .py files changed vs ``base`` (plus untracked ones).

    Parses ``git diff --name-status`` rather than ``--name-only`` so
    deleted files (status ``D``) and the OLD half of a rename (``Rxxx``)
    are skipped by STATUS, not by racing the filesystem — a removed .py
    must never reach the file walker (it would error the pre-commit
    path). Git emits paths relative to the repo toplevel, so they are
    resolved there and returned relative to ``root`` (default cwd).
    Raises RuntimeError outside a git checkout (the caller turns that
    into a CLI error)."""
    import subprocess

    root = os.path.abspath(root or os.getcwd())

    def git(*args: str) -> list[str]:
        proc = subprocess.run(["git", *args], cwd=root,
                              capture_output=True, text=True, timeout=60)
        if proc.returncode != 0:
            raise RuntimeError(
                f"git {' '.join(args)} failed: {proc.stderr.strip()}")
        return [p for p in proc.stdout.split("\0") if p]

    toplevel = git("rev-parse", "--show-toplevel")[0].strip()
    files: set[str] = set()
    fields = git("diff", "--name-status", "-z", base, "--")
    i = 0
    while i < len(fields):
        status = fields[i]
        if status.startswith(("R", "C")):
            # Rxxx/Cxxx carry two paths: the old name (gone for R) and
            # the new one — only the new name is lintable.
            if i + 2 < len(fields):
                files.add(fields[i + 2])
            i += 3
        else:
            if not status.startswith("D"):      # deleted: nothing to lint
                files.add(fields[i + 1])
            i += 2
    files |= set(git("ls-files", "-o", "--exclude-standard",
                     "--full-name", "-z"))
    out = []
    for f in sorted(files):
        if not f.endswith(".py"):
            continue
        abspath = os.path.join(toplevel, f)
        # Belt and braces: a path added in the diff but removed from the
        # working tree since (or a directory shadowing it) is skipped.
        if os.path.isfile(abspath):
            out.append(os.path.relpath(abspath, root))
    return out


def main(argv: Optional[list[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for rule in sorted(all_rules(), key=lambda r: r.id):
            print(f"{rule.id}  {rule.name:28} {rule.doc}")
        return 0
    paths = args.paths or ["kubeflow_tpu"]
    if args.changed is not None:
        if args.update_baseline:
            print("--update-baseline needs a full scan, not --changed "
                  "(a changed-only rewrite would drop every other entry)",
                  file=sys.stderr)
            return 2
        try:
            paths = changed_files(args.changed)
        except RuntimeError as exc:
            print(f"--changed: {exc}", file=sys.stderr)
            return 2
        if not paths:
            print(f"0 files changed vs {args.changed}; nothing to lint")
            return 0
    if args.contracts_json:
        from kubeflow_tpu.analysis import rules_contracts

        program = build_program(paths)
        print(json.dumps(rules_contracts.contract_manifest(program),
                         indent=2, sort_keys=True))
        return 0
    baseline: Optional[Baseline] = None
    baseline_path = args.baseline
    if not args.no_baseline and not args.update_baseline:
        if baseline_path is None:
            baseline_path = find_baseline(paths)
        if baseline_path is not None and os.path.isfile(baseline_path):
            baseline = Baseline.load(baseline_path)
    result = run_lint(paths, baseline=baseline)
    if args.update_baseline:
        target = args.baseline or find_baseline(paths) or BASELINE_FILENAME
        Baseline.from_findings(result.new,
                               reason="baselined by --update-baseline; "
                                      "replace with a real justification"
                               ).save(target)
        print(f"wrote {len(result.new)} entries to {target}")
        return 0
    if args.as_json:
        print(json.dumps({
            "files_scanned": result.files_scanned,
            "wall_time_s": round(result.wall_time_s, 4),
            "findings": [f.to_json() for f in result.new],
            "baselined": [f.to_json() for f in result.baselined],
            "errors": [f.to_json() for f in result.errors],
            "ok": result.ok,
        }, indent=2))
    else:
        for f in result.errors + result.new:
            print(f.render())
        if args.show_baselined:
            for f in result.baselined:
                print(f"{f.render()}  (baselined)")
        tail = (f"{result.files_scanned} files, "
                f"{len(result.new)} finding(s), "
                f"{len(result.baselined)} baselined, "
                f"{result.wall_time_s:.2f}s")
        if baseline is not None and baseline.path:
            tail += f" ({os.path.basename(baseline.path)})"
        print(tail)
    return 0 if result.ok else 1


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
