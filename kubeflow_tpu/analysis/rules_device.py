"""Family A — device-hygiene rules.

Tuned to how this codebase dispatches work at XLA: persistent device
buffers donated through jitted programs (serve/engine.py, serve/paged.py,
serve/device_state.py), a host-side scheduler that must never block the
hot loop, and trace sets kept log-bounded by constructing every ``jax.jit``
once at init. Each rule encodes one way PRs 1–4 actually regressed (or
nearly did):

- D101 ``host-sync-in-jit``: a blocking host sync (``jax.device_get``,
  ``.item()``, ``.block_until_ready()``, ``np.asarray``, ``float()``/
  ``int()`` on a traced parameter) inside a function compiled under
  ``jax.jit`` — at best a tracer error in prod, at worst a silent
  per-call sync when the function also runs eagerly.
- D102 ``host-sync-in-hot-loop``: the same blocking syncs inside a
  ``# hot-loop`` function (the dispatch/consume path). The ONE designed
  fetch per round is annotated ``# sync-point: <reason>``.
- D103 ``full-buffer-reupload``: ``jnp.asarray``/``jnp.array``/
  ``jax.device_put`` of a persistent ``self.*`` buffer inside a hot-loop
  function — the PR-4 per-round full-table upload, as a rule.
- D104 ``donated-buffer-reuse``: an argument donated to a jitted program
  (``donate_argnums``) read again before being rebound — donated buffers
  are invalid after dispatch.
- D105 ``jit-in-loop``: ``jax.jit(...)`` constructed inside a loop or a
  hot-loop function — a fresh compile cache entry per call.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from kubeflow_tpu.analysis.core import (
    Finding, Module, Rule, jit_table, register,
)

_JIT = {"jax.jit"}
_UPLOAD = {"jax.numpy.asarray", "jax.numpy.array", "jax.device_put"}
_HOST_FETCH = {"jax.device_get"}
_HOST_NP = {"numpy.asarray", "numpy.array"}
_SYNC_METHODS = {"item", "block_until_ready"}


def _is_jit_call(mod: Module, node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and mod.qualname(node.func) in _JIT)


def _jit_target(mod: Module, call: ast.Call) -> Optional[ast.AST]:
    """The function object a ``jax.jit(...)`` call wraps: a Lambda, a
    local FunctionDef resolved by name, or (for ``partial(jax.jit, ...)``
    used as a decorator) None — decorators are handled separately."""
    if not call.args:
        return None
    arg = call.args[0]
    if isinstance(arg, ast.Lambda):
        return arg
    if isinstance(arg, ast.Name):
        scope = mod.enclosing_function(call)
        body = scope.body if scope is not None and not isinstance(
            scope, ast.Lambda) else mod.tree.body
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and stmt.name == arg.id:
                return stmt
        # module scope fallback
        for stmt in mod.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and stmt.name == arg.id:
                return stmt
    return None


def jit_regions(mod: Module) -> list[ast.AST]:
    """Every function/lambda body compiled under ``jax.jit`` that this
    module can see syntactically: ``@jax.jit`` / ``@partial(jax.jit,..)``
    decorated defs, ``jax.jit(fn_or_lambda, ...)`` wrappings, and defs
    annotated ``# traced`` (jit-wrapped from another module)."""
    regions: list[ast.AST] = []
    for node in mod.walk():
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if mod.annotation(node, "traced") is not None:
                regions.append(node)
                continue
            for dec in node.decorator_list:
                qn = mod.qualname(dec)
                if qn in _JIT:
                    regions.append(node)
                    break
                if isinstance(dec, ast.Call):
                    dqn = mod.qualname(dec.func)
                    if dqn in _JIT:
                        regions.append(node)
                        break
                    if dqn in ("functools.partial", "partial") and dec.args \
                            and mod.qualname(dec.args[0]) in _JIT:
                        regions.append(node)
                        break
        elif _is_jit_call(mod, node):
            target = _jit_target(mod, node)
            if target is not None:
                regions.append(target)
    return regions


def _params_of(fn: ast.AST) -> set[str]:
    args = getattr(fn, "args", None)
    if args is None:
        return set()
    names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
    if args.vararg:
        names.append(args.vararg.arg)
    return set(names) - {"self", "cls"}


def hot_loop_functions(mod: Module) -> list[ast.FunctionDef]:
    return mod.memo("hot_loop_functions", lambda m: [
        node for node in m.walk()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        and m.annotation(node, "hot_loop") is not None])


def _walk_own(fn: ast.AST) -> Iterable[ast.AST]:
    """Walk a function body without descending into nested defs/lambdas
    (a nested def has its own hot-loop/jit classification)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _self_attr(node: ast.AST) -> Optional[str]:
    """'self.X' (or 'self.X.Y...') rendered, when node is rooted at self."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name) and node.id == "self" and parts:
        return "self." + ".".join(reversed(parts))
    return None


def _followed_helpers(mod: Module, regions: list[ast.AST]) -> list[ast.AST]:
    """Call-following into helpers whose bodies execute traced.

    Same-module (ISSUE 7): a helper called from jit regions whose EVERY
    resolvable caller is itself traced counts as a region — host syncs
    inside are the same defect. Helpers also reachable from host-side
    code are skipped (they may be the designed host path). With a whole-
    program ``Program`` attached (ISSUE 8) the following is TRANSITIVE
    with the program's depth bound, so a jit fact propagates through a
    helper chain instead of stopping one call deep."""
    region_ids = {id(r) for r in regions}
    cg = mod.callgraph
    out: list[ast.AST] = []
    traced = set(region_ids)
    frontier = list(regions)
    depth = 1 if mod.program is None else mod.program.MAX_DEPTH
    for _ in range(depth):
        nxt: list[ast.AST] = []
        for region in frontier:
            for callee in cg.callees(region):
                if id(callee) in traced:
                    continue
                callers = cg.callers_of(callee)
                if callers and all(id(c) in traced for c in callers):
                    traced.add(id(callee))
                    out.append(callee)
                    nxt.append(callee)
        frontier = nxt
        if not frontier:
            break
    return out


@register
class HostSyncInJit(Rule):
    id = "D101"
    name = "host-sync-in-jit"
    doc = ("blocking host sync inside a jax.jit-compiled function "
           "(device_get/.item()/.block_until_ready()/np.asarray/"
           "float|int on a traced parameter), including one-level "
           "same-module helpers only ever called from jitted code")

    def check(self, mod: Module) -> Iterable[Finding]:
        seen: set[int] = set()
        regions = jit_regions(mod)
        for region in regions + _followed_helpers(mod, regions):
            if id(region) in seen:
                continue
            seen.add(id(region))
            params = _params_of(region)
            for node in _walk_own(region):
                if not isinstance(node, ast.Call):
                    continue
                qn = mod.qualname(node.func)
                if qn in _HOST_FETCH or qn in _HOST_NP:
                    yield mod.finding(
                        self, node,
                        f"'{qn}' forces a host sync inside a jitted "
                        "function; keep results device-resident")
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _SYNC_METHODS \
                        and not node.args:
                    yield mod.finding(
                        self, node,
                        f"'.{node.func.attr}()' blocks on device inside "
                        "a jitted function")
                elif isinstance(node.func, ast.Name) \
                        and node.func.id in ("float", "int", "bool") \
                        and node.args \
                        and isinstance(node.args[0], ast.Name) \
                        and node.args[0].id in params:
                    yield mod.finding(
                        self, node,
                        f"'{node.func.id}()' on traced parameter "
                        f"'{node.args[0].id}' forces a concrete value "
                        "(host sync / tracer error) inside a jitted "
                        "function")


@register
class HostSyncInHotLoop(Rule):
    id = "D102"
    name = "host-sync-in-hot-loop"
    doc = ("blocking host sync inside a '# hot-loop' function without a "
           "'# sync-point:' justification")

    def check(self, mod: Module) -> Iterable[Finding]:
        for fn in hot_loop_functions(mod):
            for node in _walk_own(fn):
                if not isinstance(node, ast.Call):
                    continue
                qn = mod.qualname(node.func)
                hit = None
                if qn in _HOST_FETCH:
                    hit = f"'{qn}'"
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _SYNC_METHODS \
                        and not node.args:
                    hit = f"'.{node.func.attr}()'"
                elif qn == "time.sleep":
                    hit = "'time.sleep'"
                if hit is None:
                    continue
                if mod.line_annotation(node.lineno, "sync_point") is not None:
                    continue
                yield mod.finding(
                    self, node,
                    f"{hit} blocks the decode hot loop in "
                    f"'{fn.name}'; batch the fetch or mark the one "
                    "designed sync with '# sync-point: <reason>'")


@register
class FullBufferReupload(Rule):
    id = "D103"
    name = "full-buffer-reupload"
    doc = ("jnp.asarray/jnp.array/jax.device_put of a persistent self.* "
           "buffer inside a '# hot-loop' function (the PR-4 per-round "
           "full-table upload)")

    def check(self, mod: Module) -> Iterable[Finding]:
        for fn in hot_loop_functions(mod):
            for node in _walk_own(fn):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                if mod.qualname(node.func) not in _UPLOAD:
                    continue
                attr = _self_attr(node.args[0]) or (
                    _self_attr(node.args[0].value)
                    if isinstance(node.args[0], ast.Subscript) else None)
                if attr is None:
                    continue
                if mod.line_annotation(node.lineno, "sync_point") is not None:
                    continue
                yield mod.finding(
                    self, node,
                    f"full upload of persistent buffer '{attr}' every "
                    f"round in '{fn.name}'; keep it device-resident and "
                    "sync per-index deltas (serve/device_state.py)")


def _donating_callables(mod: Module) -> dict[str, tuple[int, ...]]:
    """Map of callee spellings ('self._decode_n' / 'decode_n') to donated
    positional indices — read from the shared jit-fact table
    (``core.jit_table``), the same source the F6xx dispatch-signature
    rules use, so donation facts can't drift between families."""
    return {name: fact.donate_argnums
            for name, fact in jit_table(mod).items()
            if fact.donate_argnums}


def _expr_key(node: ast.AST) -> Optional[str]:
    """Stable text for simple expressions (names / self-attr chains)."""
    if isinstance(node, ast.Name):
        return node.id
    return _self_attr(node)


def _assigned_keys(stmt: ast.stmt) -> set[str]:
    keys: set[str] = set()
    targets: list[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    for t in targets:
        for node in ast.walk(t):
            k = _expr_key(node)
            if k:
                keys.add(k)
    return keys


@register
class DonatedBufferReuse(Rule):
    id = "D104"
    name = "donated-buffer-reuse"
    doc = ("a buffer donated to a jitted dispatch (donate_argnums) is "
           "read again before being rebound")

    def check(self, mod: Module) -> Iterable[Finding]:
        donors = _donating_callables(mod)
        if not donors:
            return
        for fn in mod.walk():
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield from self._check_body(mod, fn)

    def _check_body(self, mod: Module, fn: ast.AST) -> Iterable[Finding]:
        donors = _donating_callables(mod)
        # watched donated-expression -> (callee, call line)
        watched: dict[str, tuple[str, int]] = {}

        def helper_touch(call: ast.Call, keys: set[str]
                         ) -> tuple[set[str], set[str]]:
            """One-level call-following: (reads, writes) of watched
            ``self.*`` keys inside a same-class helper this call resolves
            to. A helper that writes the key rebinds it (no finding); one
            that only reads it is a donated-buffer use."""
            target = mod.callgraph.resolve_call(call, fn)
            self_keys = {k for k in keys if k.startswith("self.")}
            if target is None or not self_keys \
                    or not isinstance(call.func, ast.Attribute):
                return set(), set()
            reads: set[str] = set()
            writes: set[str] = set()
            for node in ast.walk(target):
                if isinstance(node, (ast.Assign, ast.AugAssign,
                                     ast.AnnAssign)):
                    targets = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    for t in targets:
                        k = _expr_key(t)
                        if k in self_keys:
                            writes.add(k)
                elif isinstance(node, ast.Attribute) \
                        and isinstance(node.ctx, ast.Load):
                    k = _expr_key(node)
                    if k in self_keys:
                        reads.add(k)
            return reads - writes, writes

        def process(nodes: list[ast.AST], stmt: ast.stmt,
                    rebound: set[str]) -> Iterable[Finding]:
            """Handle the expression payload of ONE statement (a simple
            statement's whole tree, or just a compound statement's
            header): reads of watched buffers, then new donations."""
            new_watch: dict[str, tuple[str, int]] = {}
            reads: set[str] = set()
            for root in nodes:
                for node in ast.walk(root):
                    if isinstance(node, ast.Call):
                        callee = _expr_key(node.func)
                        if callee in donors:
                            for pos in donors[callee]:
                                if pos < len(node.args):
                                    key = _expr_key(node.args[pos])
                                    if key:
                                        new_watch[key] = (callee,
                                                          node.lineno)
                        elif watched:
                            h_reads, h_writes = helper_touch(
                                node, set(watched))
                            reads.update(k for k in h_reads
                                         if k not in rebound)
                            for k in h_writes:
                                watched.pop(k, None)
                    if isinstance(node, (ast.Name, ast.Attribute)):
                        k = _expr_key(node)
                        if k in watched and k not in rebound:
                            reads.add(k)
            for k in sorted(reads):
                callee, _line = watched.pop(k)
                yield Finding(
                    rule=self.id, name=self.name, path=mod.relpath,
                    line=stmt.lineno, col=stmt.col_offset + 1,
                    message=(f"'{k}' was donated to '{callee}' and is "
                             "used again without being rebound; donated "
                             "buffers are invalid after dispatch"),
                    symbol=mod.symbol_for(stmt))
            for k in rebound:
                watched.pop(k, None)
            for k, v in new_watch.items():
                if k not in rebound:
                    watched[k] = v

        _BODY_FIELDS = ("body", "orelse", "finalbody")

        def scan(stmts: list[ast.stmt]) -> Iterable[Finding]:
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue    # visited separately by check()
                compound = any(getattr(stmt, f, None) for f in _BODY_FIELDS)
                if not compound:
                    yield from process([stmt], stmt, _assigned_keys(stmt))
                    continue
                # compound: only the header expressions execute "here";
                # the bodies are scanned statement-by-statement below.
                header: list[ast.AST] = []
                for f in ("test", "iter", "subject"):
                    v = getattr(stmt, f, None)
                    if v is not None:
                        header.append(v)
                for item in getattr(stmt, "items", []) or []:
                    header.append(item.context_expr)
                if header:
                    yield from process(header, stmt, set())
                # Branches are mutually exclusive: each starts from the
                # same snapshot; survivors union afterwards (a donation in
                # one branch must not read as a use in its sibling).
                snapshot = dict(watched)
                survivors: dict[str, tuple[str, int]] = {}
                bodies = [getattr(stmt, f, None) for f in _BODY_FIELDS]
                bodies += [h.body for h in
                           (getattr(stmt, "handlers", []) or [])]
                for sub in bodies:
                    if not sub:
                        continue
                    watched.clear()
                    watched.update(snapshot)
                    yield from scan(sub)
                    survivors.update(watched)
                watched.clear()
                watched.update(survivors)

        body = getattr(fn, "body", [])
        yield from scan(body)


@register
class JitInLoop(Rule):
    id = "D105"
    name = "jit-in-loop"
    doc = ("jax.jit(...) constructed inside a loop or hot-loop function "
           "(per-call retrace / compile-cache churn)")

    def check(self, mod: Module) -> Iterable[Finding]:
        hot = {id(fn) for fn in hot_loop_functions(mod)}
        for node in mod.walk():
            if not _is_jit_call(mod, node):
                continue
            cur = getattr(node, "_parent", None)
            in_loop = False
            while cur is not None:
                if isinstance(cur, (ast.For, ast.While, ast.AsyncFor)):
                    in_loop = True
                    break
                if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if id(cur) in hot:
                        in_loop = True
                    break
                cur = getattr(cur, "_parent", None)
            if in_loop:
                yield mod.finding(
                    self, node,
                    "jax.jit constructed per iteration; build it once "
                    "at init and reuse the compiled program")
