"""Family R — resource pairing & lock-ordering rules (ISSUE 7 tentpole).

The refcounted ``PageAllocator`` (serve/paged.py) is about to be shared
across requests (ROADMAP item 1): every alloc must be balanced by exactly
one free even when the statement after the alloc raises, every test that
touches the pool must prove quiescence, and the threaded control plane
must acquire its locks in one global order. Statically:

- R501 ``leaked-alloc``: pages allocated (``*allocator*.alloc(...)``)
  with a statement that can raise between the alloc and the point where
  ownership is recorded, and no ``try`` handler/finally that frees them —
  the exception path leaks the pages (built on the shared
  ``core.leaky_allocs`` pairing primitive).
- R502 ``unaudited-paged-test``: a test function that builds a paged
  engine/pool (``paged=True`` or ``PageAllocator(...)``) but never —
  directly or via a one-level helper — calls ``assert_quiescent`` /
  ``kv_pages_in_use``. Applies to test files only (``tests/`` or
  ``test_*.py``).
- R503 ``lock-order-inversion``: build the lock-acquisition order graph
  (lock L2 acquired while L1 is held, including one level through
  same-module helper methods) from the same class models C301 uses, and
  report each cycle once. The runtime half is the
  ``KFTPU_SANITIZE=lockorder`` watchdog (runtime/sanitize.py), which
  records the REAL acquisition graph and fails on a cycle.
- R504 ``unhandled-checkpoint-io`` (ISSUE 9 survivability): a
  ``CheckpointManager.save``/``.restore`` call site (receiver spelled
  ``ckpt``/``checkpoint``, how this codebase names them) with no
  exception or return handling. ``restore`` raises
  ``CheckpointCorruptionError`` on a bad step — an unguarded call turns
  a corrupt checkpoint into a dead job instead of a fallback; ``save``
  returns an acceptance bool and can raise on storage failure — a bare
  expression call drops rejected saves silently, the exact
  ``Trainer.save`` bug this PR fixed. Production code only (test files
  exercise these paths raw on purpose).
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from kubeflow_tpu.analysis.core import (
    Finding, Module, Rule, leaky_allocs, register,
)
from kubeflow_tpu.analysis.rules_concurrency import (
    _ClassModel, _self_attr_name, class_models,
)


def _attr_chain(node: ast.AST) -> str:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _is_page_alloc(call: ast.Call) -> bool:
    """``<something allocator-ish>.alloc(...)`` — tuned to how this
    codebase spells it (engine._allocator, a local ``allocator``/``pool``
    variable, or the PageAllocator instance in tests)."""
    if not isinstance(call.func, ast.Attribute) or call.func.attr != "alloc":
        return False
    recv = _attr_chain(call.func.value).lower()
    return any(s in recv for s in ("alloc", "pool", "pages"))


def _releases_pages(stmt: ast.stmt, var: str) -> bool:
    """Ownership of ``var`` is taken or returned: freed, recorded into a
    structure, returned, or passed onward."""
    for node in ast.walk(stmt):
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("free", "extend", "append",
                                           "incref"):
                if any(isinstance(a, ast.Name) and a.id == var
                       for a in node.args):
                    return True
            # ownership handed to any callee that receives the var
            if any(isinstance(a, ast.Name) and a.id == var
                   for a in node.args):
                return True
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, (ast.Subscript, ast.Attribute)):
                    for sub in ast.walk(node.value):
                        if isinstance(sub, ast.Name) and sub.id == var:
                            return True
        elif isinstance(node, ast.Return) and node.value is not None:
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Name) and sub.id == var:
                    return True
    return False


@register
class LeakedAlloc(Rule):
    id = "R501"
    name = "leaked-alloc"
    doc = ("page alloc with a raise-capable statement before ownership "
           "is recorded and no handler/finally that frees — the "
           "exception path leaks the pages")

    def check(self, mod: Module) -> Iterable[Finding]:
        for fn in mod.walk():
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for alloc, var, risky in leaky_allocs(
                    fn, _is_page_alloc, _releases_pages):
                where = ("never recorded or freed"
                         if risky is getattr(alloc, "_parent", None) else
                         f"line {risky.lineno} can raise first")
                yield mod.finding(
                    self, alloc,
                    f"pages allocated into '{var}' in '{fn.name}' can "
                    f"leak on an exception path ({where}); record "
                    "ownership immediately or free in a handler/finally")


def _is_test_path(relpath: str) -> bool:
    parts = relpath.split("/")
    return "tests" in parts or parts[-1].startswith("test_")


@register
class UnauditedPagedTest(Rule):
    id = "R502"
    name = "unaudited-paged-test"
    doc = ("test touches the paged KV pool (paged=True / "
           "PageAllocator) without asserting quiescence "
           "(assert_quiescent / kv_pages_in_use); test files only")

    _AUDITS = ("assert_quiescent", "kv_pages_in_use")

    def check(self, mod: Module) -> Iterable[Finding]:
        if not _is_test_path(mod.relpath):
            return
        cg = mod.callgraph

        def touches_pool(fn: ast.AST) -> bool:
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                if any(kw.arg == "paged"
                       and isinstance(kw.value, ast.Constant)
                       and kw.value.value is True
                       for kw in node.keywords):
                    return True
                qn = mod.qualname(node.func) or ""
                if qn.split(".")[-1] == "PageAllocator":
                    return True
            return False

        def audits(fn: ast.AST) -> bool:
            for node in ast.walk(fn):
                if isinstance(node, ast.Attribute) \
                        and node.attr in self._AUDITS:
                    return True
            return False

        for fn in mod.walk():
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not fn.name.startswith("test_"):
                continue
            followed = [fn] + cg.callees(fn)
            if not any(touches_pool(f) for f in followed):
                continue
            if any(audits(f) for f in followed):
                continue
            yield mod.finding(
                self, fn,
                f"'{fn.name}' touches the paged KV pool but never "
                "audits refcount balance; call assert_quiescent() (or "
                "kv_pages_in_use()==0) before teardown")


@register
class LockOrderInversion(Rule):
    id = "R503"
    name = "lock-order-inversion"
    doc = ("cyclic lock-acquisition order across the module's classes "
           "(lock B taken under lock A in one path, A under B in "
           "another) — the static half of KFTPU_SANITIZE=lockorder")

    def check(self, mod: Module) -> Iterable[Finding]:
        edges: dict[tuple[str, str], list[tuple[str, str], ]] = {}
        sites: dict[tuple[str, str], ast.AST] = {}
        for cm in class_models(mod):
            if not cm.lock_attrs:
                continue
            self._class_edges(mod, cm, edges, sites)
        yield from self._cycles(mod, edges, sites)

    # -- edge collection ---------------------------------------------------

    def _class_edges(self, mod: Module, cm: _ClassModel, edges, sites
                     ) -> None:
        cls = cm.cls.name

        def node_of(attr: str) -> str:
            return f"{cls}.{cm._canonical_lock(attr)}"

        def direct_acquires(fn: ast.AST) -> list[tuple[str, ast.AST]]:
            out = []
            for node in ast.walk(fn):
                if isinstance(node, ast.With):
                    for item in node.items:
                        a = _self_attr_name(item.context_expr)
                        if a and a in cm.lock_attrs:
                            out.append((node_of(a), node))
            return out

        def visit(fn_name: str, fn: ast.AST, node: ast.AST,
                  held: tuple) -> None:
            if isinstance(node, ast.With):
                acquired = []
                for item in node.items:
                    a = _self_attr_name(item.context_expr)
                    if a and a in cm.lock_attrs:
                        acquired.append(node_of(a))
                for lk in acquired:
                    for h in held:
                        if h != lk:
                            edges.setdefault((h, lk), []).append(
                                (cls, fn_name))
                            sites.setdefault((h, lk), node)
                inner = held + tuple(acquired)
                for child in node.body:
                    visit(fn_name, fn, child, inner)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return
            if held and isinstance(node, ast.Call):
                # one-level follow: a helper's direct acquisitions happen
                # under everything held here
                target = mod.callgraph.resolve_call(node, fn)
                if target is not None:
                    t_cls = mod.callgraph.enclosing_class(target)
                    t_cm = self._model_for(mod, t_cls)
                    if t_cm is not None:
                        for lk, site in self._direct_of(t_cm, target):
                            for h in held:
                                if h != lk:
                                    edges.setdefault((h, lk), []).append(
                                        (cls, fn_name))
                                    sites.setdefault((h, lk), node)
            for child in ast.iter_child_nodes(node):
                visit(fn_name, fn, child, held)

        for name, fn in cm.methods.items():
            base = tuple(sorted(
                node_of(a) for a in cm._method_locks(name, fn)))
            for stmt in fn.body:
                visit(name, fn, stmt, base)

    _models_cache: Optional[dict] = None

    def _model_for(self, mod: Module, cls_name: Optional[str]):
        if cls_name is None:
            return None
        for cm in class_models(mod):
            if cm.cls.name == cls_name:
                return cm
        return None

    @staticmethod
    def _direct_of(cm: _ClassModel, fn: ast.AST
                   ) -> list[tuple[str, ast.AST]]:
        out = []
        for node in ast.walk(fn):
            if isinstance(node, ast.With):
                for item in node.items:
                    a = _self_attr_name(item.context_expr)
                    if a and a in cm.lock_attrs:
                        out.append(
                            (f"{cm.cls.name}.{cm._canonical_lock(a)}",
                             node))
        return out

    # -- cycle detection ---------------------------------------------------

    def _cycles(self, mod: Module, edges, sites) -> Iterable[Finding]:
        adj: dict[str, set[str]] = {}
        for a, b in edges:
            adj.setdefault(a, set()).add(b)
        seen_cycles: set[frozenset] = set()
        for start in sorted(adj):
            stack = [(start, (start,))]
            while stack:
                cur, path = stack.pop()
                for nxt in sorted(adj.get(cur, ())):
                    if nxt == start:
                        key = frozenset(path)
                        if key in seen_cycles:
                            continue
                        seen_cycles.add(key)
                        cycle = list(path) + [start]
                        edge = (path[-1], start)
                        where = sites.get(edge) or sites.get(
                            (start, path[1] if len(path) > 1 else start))
                        methods = sorted({
                            f"{c}.{m}" for e in zip(cycle, cycle[1:])
                            for c, m in edges.get(e, ())})
                        yield mod.finding(
                            self, where if where is not None else
                            mod.tree.body[0],
                            "lock-order inversion: "
                            + " -> ".join(cycle)
                            + f" (acquired in {', '.join(methods)}); "
                            "pick one global order",
                            symbol="|".join(sorted(set(cycle))))
                    elif nxt not in path:
                        stack.append((nxt, path + (nxt,)))


@register
class UnhandledCheckpointIO(Rule):
    id = "R504"
    name = "unhandled-checkpoint-io"
    doc = ("CheckpointManager save/restore call with no exception or "
           "return handling — restore raises on a corrupt step (crash "
           "instead of fallback), save's acceptance bool silently drops "
           "rejected saves; production code only")

    _CKPT_HINTS = ("ckpt", "checkpoint")

    def check(self, mod: Module) -> Iterable[Finding]:
        if _is_test_path(mod.relpath):
            return
        for call in mod.walk(ast.Call):
            f = call.func
            if not isinstance(f, ast.Attribute) \
                    or f.attr not in ("save", "restore"):
                continue
            recv = _attr_chain(f.value).lower()
            if not any(h in recv for h in self._CKPT_HINTS):
                continue
            if self._handled_by_try(call):
                continue
            if f.attr == "save":
                parent = getattr(call, "_parent", None)
                if not isinstance(parent, ast.Expr):
                    continue        # acceptance bool consumed
                yield mod.finding(
                    self, call,
                    f"'{recv}.save(...)' drops the acceptance bool and has "
                    "no exception handling — a rejected or failed save "
                    "vanishes silently; check the return (count/log "
                    "failures) or wrap in try/except")
            else:
                yield mod.finding(
                    self, call,
                    f"'{recv}.restore(...)' has no exception handling — "
                    "restore raises CheckpointCorruptionError on a bad "
                    "step, so this call turns a corrupt checkpoint into a "
                    "crash instead of a fallback (see "
                    "train/checkpoint.py::resume_from_tiers)")

    @staticmethod
    def _handled_by_try(node: ast.AST) -> bool:
        """Any enclosing try-with-except inside the same function counts
        as handling (else/finally placement included — the author thought
        about the failure path)."""
        cur = getattr(node, "_parent", None)
        while cur is not None and not isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            if isinstance(cur, ast.Try) and cur.handlers:
                return True
            cur = getattr(cur, "_parent", None)
        return False
