"""``python -m kubeflow_tpu.analysis`` — same as ``kftpu lint``."""

import sys

from kubeflow_tpu.analysis.core import main

if __name__ == "__main__":
    sys.exit(main())
