"""Family S — sharding / SPMD correctness rules (ISSUE 7 tentpole).

The next platform steps are a 3-D GSPMD ``pjit`` mesh and cross-request KV
sharing — exactly the territory where a silent sharding mistake costs 2×
HBM (an undonated carry), a wrong collective (a typo'd axis name), or a
per-round host round-trip. These rules encode the mesh/sharding contracts
the codebase already follows:

- S401 ``undonated-carry``: a ``jax.jit``/``pjit`` callable constructed
  WITHOUT ``donate_argnums`` whose call sites are carry-style — an
  argument expression reappears among the call's assignment targets
  (``self.cache = self._fn(self.cache)``). The old buffer stays resident
  while the new one materializes: 2× HBM for the platform's biggest
  arrays.
- S402 ``unknown-mesh-axis``: a hard-coded mesh-axis string in an axis
  position (``PartitionSpec``/``NamedSharding`` specs, ``Mesh`` axis
  names, ``axis_name=`` keywords) that is not one of the canonical axis
  names from ``runtime/mesh.py``'s ``MESH_AXES``. GSPMD treats an unknown
  axis as a fresh size-1 axis — the op silently stops being sharded.
- S403 ``host-round-trip``: a value fetched to host (``jax.device_get``,
  ``np.asarray``, ``.item()``) flows back into a jitted dispatch in the
  same function — a device→host→device bounce per call on the value's
  own dispatch path.
- S404 ``implicit-replication``: ``jax.device_put`` of a params/weights
  pytree with no sharding argument in a module that works with meshes —
  every chip gets a full copy; ``parallel/sharding.shard_params`` exists
  for exactly this call.
- S405 ``unbound-collective``: a collective (``psum``/``all_gather``/
  ``ppermute``/...) with a LITERAL ``axis_name`` in a function this
  module never places under ``shard_map``/``pjit`` (by the one-level call
  graph) and that isn't annotated ``# mesh-context: <reason>`` — at best
  a NameError at trace time, at worst a collective over the wrong axis
  when an outer binding happens to share the name.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from kubeflow_tpu.analysis.core import (
    Finding, Module, Rule, canonical_mesh_axes, jit_table, register,
)

_SPEC_QNS = {
    "jax.sharding.PartitionSpec",
    "jax.sharding.NamedSharding",     # axis literals ride in its spec arg
}
_MESH_QNS = {"jax.sharding.Mesh", "jax.make_mesh"}
_HOST_FETCH_QNS = {"jax.device_get", "numpy.asarray", "numpy.array"}
_COLLECTIVE_QNS = {
    "jax.lax.psum", "jax.lax.pmean", "jax.lax.pmax", "jax.lax.pmin",
    "jax.lax.all_gather", "jax.lax.all_to_all", "jax.lax.ppermute",
    "jax.lax.psum_scatter", "jax.lax.axis_index", "jax.lax.axis_size",
}
_SHARD_MAP_QNS = {
    "jax.shard_map",
    "jax.experimental.shard_map.shard_map",
    "kubeflow_tpu.compat.shard_map",
}


def _expr_key(node: ast.AST) -> Optional[str]:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        return ".".join([node.id] + list(reversed(parts)))
    return None


def _jit_assignments(mod: Module) -> dict[str, tuple[ast.AST, bool]]:
    """Jitted-callable spellings with their donation flag, read from the
    shared jit-fact table (``core.jit_table``) — assignments and
    ``@partial(jax.jit, ...)`` decorations alike; bare-decorated defs
    are excluded (their ctor carries no argument spec to inspect)."""
    return {name: (fact.ctor, fact.donates)
            for name, fact in jit_table(mod).items()
            if isinstance(fact.ctor, ast.Call)}


@register
class UndonatedCarry(Rule):
    id = "S401"
    name = "undonated-carry"
    doc = ("jit/pjit callable called carry-style (an argument returns "
           "into itself) but constructed without donate_argnums — the "
           "old buffer stays resident: 2x HBM on the carry")

    def check(self, mod: Module) -> Iterable[Finding]:
        ctors = _jit_assignments(mod)
        undonated = {n: c for n, (c, d) in ctors.items() if not d}
        if not undonated:
            return
        reported: set[str] = set()
        for node in mod.walk():
            if not isinstance(node, ast.Assign):
                continue
            call = node.value
            if not isinstance(call, ast.Call):
                continue
            callee = _expr_key(call.func)
            if callee not in undonated or callee in reported:
                continue
            target_keys: set[str] = set()
            for t in node.targets:
                elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) \
                    else [t]
                for e in elts:
                    k = _expr_key(e)
                    if k:
                        target_keys.add(k)
            carried = sorted(
                k for k in (_expr_key(a) for a in call.args)
                if k and k in target_keys)
            if not carried:
                continue
            reported.add(callee)
            ctor = undonated[callee]
            yield mod.finding(
                self, ctor,
                f"'{callee}' is called carry-style ('{carried[0]}' "
                f"returns into its own argument at line {node.lineno}) "
                "but has no donate_argnums; donate the carry so the old "
                "buffer's HBM is reused")


def _axis_literals(node: ast.AST) -> Iterable[ast.Constant]:
    """String constants in an axis position of ``node`` (a spec/axis
    argument): bare strings and strings inside tuples/lists."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        yield node
    elif isinstance(node, (ast.Tuple, ast.List)):
        for e in node.elts:
            yield from _axis_literals(e)
    elif isinstance(node, ast.BoolOp):
        # `batch_axes or None` — literals live in the operands
        for v in node.values:
            yield from _axis_literals(v)
    elif isinstance(node, ast.Starred):
        yield from _axis_literals(node.value)


@register
class UnknownMeshAxis(Rule):
    id = "S402"
    name = "unknown-mesh-axis"
    doc = ("hard-coded mesh-axis string that is not a canonical axis "
           "name from runtime/mesh.py MESH_AXES (GSPMD silently treats "
           "it as an unsharded fresh axis)")

    def check(self, mod: Module) -> Iterable[Finding]:
        axes = set(canonical_mesh_axes())
        for node in mod.walk():
            if not isinstance(node, ast.Call):
                continue
            qn = mod.qualname(node.func)
            spots: list[ast.AST] = []
            if qn in _SPEC_QNS and qn.endswith("PartitionSpec"):
                spots.extend(node.args)
            elif qn in _MESH_QNS:
                # Mesh(devices, axis_names) / make_mesh(shape, axis_names)
                spots.extend(node.args[1:2])
                spots.extend(kw.value for kw in node.keywords
                             if kw.arg == "axis_names")
            elif qn in _COLLECTIVE_QNS:
                spots.extend(node.args[1:2])
                spots.extend(kw.value for kw in node.keywords
                             if kw.arg == "axis_name")
            else:
                spots.extend(kw.value for kw in node.keywords
                             if kw.arg == "axis_name")
            for spot in spots:
                for lit in _axis_literals(spot):
                    if lit.value not in axes:
                        yield mod.finding(
                            self, lit,
                            f"mesh axis {lit.value!r} is not a canonical "
                            f"axis name ({', '.join(sorted(axes))}); a "
                            "typo'd axis silently unshards the op")


class _TaintVisitor:
    """Order-aware single-function taint: vars assigned from a host fetch
    (device_get / np.asarray / .item()) are tainted; so is anything
    assigned FROM a tainted var. A tainted var appearing in the arguments
    of a known-jitted callable is the round trip."""

    def __init__(self, mod: Module, jitted: set[str]):
        self.mod = mod
        self.jitted = jitted
        self.tainted: set[str] = set()

    def _is_fetch(self, call: ast.Call) -> bool:
        qn = self.mod.qualname(call.func)
        if qn in _HOST_FETCH_QNS:
            return True
        return (isinstance(call.func, ast.Attribute)
                and call.func.attr == "item" and not call.args)

    def _mentions_taint(self, node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and sub.id in self.tainted:
                return True
        return False

    def scan(self, fn: ast.AST) -> Iterable[tuple[ast.Call, str]]:
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                src_tainted = (
                    (isinstance(node.value, ast.Call)
                     and self._is_fetch(node.value))
                    or self._mentions_taint(node.value))
                for t in node.targets:
                    elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) \
                        else [t]
                    for e in elts:
                        if isinstance(e, ast.Name):
                            if src_tainted:
                                self.tainted.add(e.id)
                            else:
                                self.tainted.discard(e.id)
            elif isinstance(node, ast.Call):
                callee = _expr_key(node.func)
                if callee in self.jitted:
                    for a in node.args:
                        for sub in ast.walk(a):
                            if isinstance(sub, ast.Name) \
                                    and sub.id in self.tainted:
                                yield node, sub.id
                                break
                        else:
                            continue
                        break


@register
class HostRoundTrip(Rule):
    id = "S403"
    name = "host-round-trip"
    doc = ("a host-fetched value (device_get/np.asarray/.item()) flows "
           "back into a jitted dispatch in the same function — a "
           "device->host->device bounce per call")

    def check(self, mod: Module) -> Iterable[Finding]:
        jitted = set(_jit_assignments(mod))
        if not jitted:
            return
        for fn in mod.walk():
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            visitor = _TaintVisitor(mod, jitted)
            for call, var in visitor.scan(fn):
                yield mod.finding(
                    self, call,
                    f"'{var}' was fetched to host earlier in "
                    f"'{fn.name}' and rides back into the jitted "
                    f"dispatch '{_expr_key(call.func)}'; keep the value "
                    "device-resident across the round trip")


_PARAMISH = ("param", "weight", "state_dict")


@register
class ImplicitReplication(Rule):
    id = "S404"
    name = "implicit-replication"
    doc = ("jax.device_put of a params/weights pytree without a sharding "
           "argument in a mesh-aware module — every chip gets a full "
           "replica; use parallel/sharding.shard_params")

    def check(self, mod: Module) -> Iterable[Finding]:
        text = mod.text
        mesh_aware = ("NamedSharding" in text or "make_mesh" in text
                      or "parallel.sharding" in text
                      or "Mesh(" in text)
        if not mesh_aware:
            return
        for node in mod.walk():
            if not isinstance(node, ast.Call):
                continue
            if mod.qualname(node.func) != "jax.device_put":
                continue
            if len(node.args) >= 2 or any(
                    kw.arg in ("device", "sharding")
                    for kw in node.keywords):
                continue
            if not node.args:
                continue
            key = (_expr_key(node.args[0]) or "").lower()
            if any(p in key for p in _PARAMISH):
                yield mod.finding(
                    self, node,
                    f"device_put of '{_expr_key(node.args[0])}' without "
                    "a sharding in a mesh-aware module replicates the "
                    "full pytree on every chip; pass shard_params(...) "
                    "(parallel/sharding.py)")


@register
class UnboundCollective(Rule):
    id = "S405"
    name = "unbound-collective"
    doc = ("collective with a literal axis_name in a function this "
           "module never places under shard_map/pjit; annotate "
           "'# mesh-context: <reason>' if the caller binds it")

    def check(self, mod: Module) -> Iterable[Finding]:
        cg = mod.callgraph
        bound: set[int] = set()
        # functions handed to shard_map (by name) are bound; so is
        # anything THEY call (one level), and jit-wrapped/# traced defs
        # (pjit axes bind via the mesh context manager at dispatch).
        for node in mod.walk():
            if not isinstance(node, ast.Call):
                continue
            if mod.qualname(node.func) in _SHARD_MAP_QNS and node.args:
                tgt = node.args[0]
                fn = None
                if isinstance(tgt, ast.Name):
                    fn = cg.module_fns.get(tgt.id)
                elif isinstance(tgt, ast.Call):
                    # shard_map(partial(fn, ...)) — first partial arg
                    inner = tgt.args[0] if tgt.args else None
                    if isinstance(inner, ast.Name):
                        fn = cg.module_fns.get(inner.id)
                if fn is not None:
                    bound.add(id(fn))
                    for callee in cg.callees(fn):
                        bound.add(id(callee))
        for fn in mod.walk():
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if id(fn) in bound:
                continue
            if mod.annotation(fn, "mesh_context") is not None \
                    or mod.annotation(fn, "traced") is not None:
                continue
            # a fn whose CALLERS are all bound is bound too (one level up)
            callers = cg.callers_of(fn)
            if callers and all(id(c) in bound for c in callers):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                if mod.qualname(node.func) not in _COLLECTIVE_QNS:
                    continue
                axis = None
                if len(node.args) >= 2:
                    axis = node.args[1]
                for kw in node.keywords:
                    if kw.arg == "axis_name":
                        axis = kw.value
                if isinstance(axis, ast.Constant) \
                        and isinstance(axis.value, str):
                    yield mod.finding(
                        self, node,
                        f"collective over literal axis "
                        f"{axis.value!r} in '{fn.name}', which this "
                        "module never places under shard_map/jit; bind "
                        "the axis or annotate '# mesh-context:'")
