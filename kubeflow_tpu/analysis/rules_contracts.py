"""Family X — cross-component name-contract rules (ISSUE 10 tentpole).

The platform's control loops are wired together by *names*: the SLO
autoscaler scrapes literal series names the engine emits through
``obs/registry``; QoS/deadline/trace semantics ride ``X-Kftpu-*``
headers; gang rendezvous rides ``KFTPU_*`` env vars produced in
``runtime/bootstrap`` and consumed in ``worker_main``; the goodput
ledger's JSON fields are lifted onto job status by literal key. A rename
on either side of any of those pairs breaks nothing at import time and
no single-file rule can see it — the consumer just reads ``None``
forever and the control loop silently HOLDs. These rules extract a
whole-program **contract table** from the PR-8 ``Program`` and check
both sides of every pair:

- X701 ``consumed-series-never-produced``: a literal metric-series name
  compared against ``parse_exposition`` output (or listed in a scrape
  set) that no registry definition site produces — the renamed-producer
  half of autoscaler blindness. Producers include the M-rule f-string
  loop expansion and dynamic f-string heads (prefix match).
- X702 ``produced-series-unconsumed-undocumented``: an exact series name
  registered somewhere but neither consumed in the scan set nor listed
  in the README metric catalog — dead telemetry, or the renamed-consumer
  half of the same drift.
- X703 ``header-contract-drift``: an ``X-Kftpu-*`` header read that
  nothing sets (typo/stale consumer), set that nothing reads, spelled
  with drifting case across sites, or exchanged on the serving path but
  missing from the middlebox forward-list (``core/headers.
  FORWARD_HEADERS`` — a proxy that drops it silently breaks deadlines/
  QoS/tracing through it).
- X704 ``orphan-env-var``: a ``KFTPU_*`` env var read that nothing
  writes into a child environment (or ``os.environ``), or written but
  never read — the rendezvous-boundary rename.
- X705 ``status-field-drift``: a JSON field name read off a parsed
  record (``m = json.loads(...)`` then ``m.get("field")``, including the
  literal-tuple loop idiom) that no writer produces as a dict key — the
  metrics.jsonl → job-status scrape boundary.

Extraction is tuned to how THIS codebase spells each exchange (the
analyzer's standing philosophy): series consumption is a
``kftpu_``-literal inside a comparison or literal container; header and
env names resolve through module-level string constants across modules
(``from kubeflow_tpu.core.headers import QOS_HEADER`` carries the
spelling to every use site), so centralized constants keep working while
re-typed literals are checked letter by letter. Histogram families match
their ``_bucket``/``_sum``/``_count`` fan-out.

Escape: ``# contract: <reason>`` on the site line (or the line above)
marks a name as intentionally one-sided — a user-facing knob nothing in
the tree sets, a value exported for consumers outside the lint scan —
with the reason on record. ``# lint: disable=X70x`` suppresses a single
rule.

``contract_manifest(program)`` serializes the whole table — the
``kftpu lint --contracts-json`` document the runtime contract auditor
(``KFTPU_SANITIZE=contract``, runtime/sanitize.py) diffs its observed
exchanges against.

With no ``Program`` attached (bare ``lint_source`` fixtures) the X-rules
stay SILENT rather than degrade: a cross-component judgment made from
one module alone would flag every one-module view of a two-module
contract. Fixtures exercise the family through ``lint_sources``, which
wires a ``Program`` even for a single module.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterable, Optional

from kubeflow_tpu.analysis.core import Module, Program, Rule, register
from kubeflow_tpu.analysis.rules_metrics import _literal_names

_SERIES_RE = re.compile(r"^kftpu_[a-z0-9_:]+$")
_HEADER_RE = re.compile(r"^X-Kftpu-[A-Za-z0-9-]+$", re.IGNORECASE)
_ENV_RE = re.compile(r"^KFTPU_[A-Z0-9_]+$")
HIST_SUFFIXES = ("_bucket", "_sum", "_count")

_REG_METHODS = {"counter", "gauge", "histogram"}
_REG_CLASSES = {
    "kubeflow_tpu.obs.registry.Counter",
    "kubeflow_tpu.obs.registry.Gauge",
    "kubeflow_tpu.obs.registry.Histogram",
}
_HEADER_SET_METHODS = {"add_header", "putheader", "send_header"}
_CONSUME_CONTEXTS = (ast.Compare, ast.List, ast.Tuple, ast.Set)


def series_base(name: str) -> str:
    for suffix in HIST_SUFFIXES:
        if name.endswith(suffix):
            return name[:-len(suffix)]
    return name


# -- module-level string constants ---------------------------------------------


def _str_consts(mod: Module) -> dict:
    """Module-level ``NAME = "literal"`` (and literal-tuple) assignments,
    in definition order so a tuple of earlier constants resolves
    (``FORWARD_HEADERS = (DEADLINE_HEADER, ...)``). Values are ``str`` or
    ``tuple[str, ...]``."""
    return mod.memo("xcontract_consts", _build_str_consts)


def _build_str_consts(mod: Module) -> dict:
    out: dict = {}
    for stmt in mod.tree.body:
        targets = stmt.targets if isinstance(stmt, ast.Assign) \
            else [stmt.target] if isinstance(stmt, ast.AnnAssign) else []
        value = getattr(stmt, "value", None)
        names = [t.id for t in targets if isinstance(t, ast.Name)]
        if not names or value is None:
            continue
        if isinstance(value, ast.Constant) and isinstance(value.value, str):
            for n in names:
                out[n] = value.value
        elif isinstance(value, (ast.Tuple, ast.List)):
            elems = []
            for e in value.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    elems.append(e.value)
                elif isinstance(e, ast.Name) and isinstance(
                        out.get(e.id), str):
                    elems.append(out[e.id])
                else:
                    elems = None
                    break
            if elems is not None:
                for n in names:
                    out[n] = tuple(elems)
    return out


def _unwrap_case_call(node: ast.AST) -> ast.AST:
    """``QOS_HEADER.lower()`` → the ``QOS_HEADER`` Name (the gRPC
    metadata spelling transport; the contract name is the constant's)."""
    if isinstance(node, ast.Call) and not node.args and not node.keywords \
            and isinstance(node.func, ast.Attribute) \
            and node.func.attr in ("lower", "upper", "title"):
        return node.func.value
    return node


def _resolve_str(mod: Module, node: ast.AST):
    """(value, pending_qualname): a literal resolves immediately; a Name
    bound to a same-module constant resolves immediately; a Name imported
    from elsewhere resolves at aggregation time through the Program
    (returned as a pending dotted qualname)."""
    node = _unwrap_case_call(node)
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value, None
    if isinstance(node, ast.Name):
        local = _str_consts(mod).get(node.id)
        if isinstance(local, str):
            return local, None
    qn = mod.qualname(node)
    if qn is not None and "." in qn:
        return None, qn
    return None, None


def _resolve_pending(program: Optional[Program], qual: str) -> Optional[str]:
    if program is None:
        return None
    parts = qual.split(".")
    for cut in range(len(parts) - 1, 0, -1):
        m2 = program.by_name.get(".".join(parts[:cut]))
        if m2 is not None:
            got = _str_consts(m2).get(".".join(parts[cut:]))
            return got if isinstance(got, str) else None
    return None


# -- per-module extraction -----------------------------------------------------


def _extract(mod: Module) -> dict:
    """All name-exchange sites one module contains, program-independent
    (cross-module constant references stay symbolic until aggregation).
    Cached on the module."""
    return mod.memo("xcontract_extract", _build_extract)


def _is_definition_site(mod: Module, call: ast.Call) -> bool:
    if not isinstance(call, ast.Call) or not call.args:
        return False
    if isinstance(call.func, ast.Attribute) \
            and call.func.attr in _REG_METHODS:
        return True
    return mod.qualname(call.func) in _REG_CLASSES


def _in_consume_context(node: ast.AST) -> bool:
    """A series literal counts as CONSUMED when it sits in a comparison
    or a literal container (scrape sets, match chains) — not when it is
    a bare assignment value, a call argument (ContextVar names, log
    strings), or a dict key."""
    cur = getattr(node, "_parent", None)
    while cur is not None and not isinstance(cur, ast.stmt):
        if isinstance(cur, _CONSUME_CONTEXTS):
            return True
        if isinstance(cur, (ast.Call, ast.Dict, ast.JoinedStr)):
            return False
        cur = getattr(cur, "_parent", None)
    return False


def _loop_fills(fn: Optional[ast.AST], var: str,
                node: ast.AST) -> Optional[list[str]]:
    """Literal values ``var`` takes in an enclosing ``for var in ("a",
    ...)`` loop inside ``fn`` (the ``for field in (...): m.get(field)``
    consumption idiom), else None."""
    cur = getattr(node, "_parent", None)
    while cur is not None and cur is not fn:
        if isinstance(cur, ast.For) and isinstance(cur.target, ast.Name) \
                and cur.target.id == var \
                and isinstance(cur.iter, (ast.Tuple, ast.List, ast.Set)):
            vals = [e.value for e in cur.iter.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)]
            if len(vals) == len(cur.iter.elts):
                return vals
        cur = getattr(cur, "_parent", None)
    return None


def _build_extract(mod: Module) -> dict:
    out = {
        "series_produced": [],    # (name, node, exact, is_hist)
        "series_prefix": [],      # (prefix, node) — dynamic f-string heads
        "series_consumed": [],    # (name, node)
        "headers_set": [],        # (spelling, node) | pending
        "headers_read": [],       # (spelling, node) | pending
        "headers_pending": [],    # (qualname, direction, node)
        "forward_list": None,     # (names, node)
        "env_set": [],            # (name, node) | via constants
        "env_read": [],
        "env_pending": [],        # (qualname, direction, node)
        "fields_produced": set(),
        "fields_consumed": [],    # (name, node)
    }

    # Metric series: definition sites (with the M-rule loop expansion)...
    for node in mod.walk(ast.Call):
        if not _is_definition_site(mod, node):
            continue
        is_hist = (isinstance(node.func, ast.Attribute)
                   and node.func.attr == "histogram") \
            or (mod.qualname(node.func) or "").endswith("Histogram")
        for name, exact in _literal_names(node.args[0]):
            if not name.startswith("kftpu_"):
                continue      # a bad prefix is M201's finding, not X's
            if exact:
                out["series_produced"].append((name, node, True, is_hist))
            else:
                out["series_prefix"].append((name, node))

    # ...and consumption sites: kftpu_ literals in comparisons/containers.
    for node in mod.walk(ast.Constant):
        if not isinstance(node.value, str) \
                or not _SERIES_RE.match(node.value):
            continue
        parent = getattr(node, "_parent", None)
        if isinstance(parent, ast.Call) and _is_definition_site(mod, parent) \
                and parent.args and parent.args[0] is node:
            continue
        if _in_consume_context(node):
            out["series_consumed"].append((node.value, node))

    def note_header(node: ast.AST, direction: str) -> None:
        value, pending = _resolve_str(mod, node)
        if value is not None and _HEADER_RE.match(value):
            out[f"headers_{direction}"].append((value, node))
        elif pending is not None:
            out["headers_pending"].append((pending, direction, node))

    def note_env(node: ast.AST, direction: str) -> None:
        value, pending = _resolve_str(mod, node)
        if value is not None and _ENV_RE.match(value):
            out[f"env_{direction}"].append((value, node))
        elif pending is not None:
            out["env_pending"].append((pending, direction, node))

    for node in mod.walk(ast.Call):
        if not isinstance(node.func, ast.Attribute) or not node.args:
            continue
        if node.func.attr in _HEADER_SET_METHODS:
            note_header(node.args[0], "set")
        elif node.func.attr == "get":
            note_header(node.args[0], "read")
            note_env(node.args[0], "read")
        elif node.func.attr in ("setdefault", "pop"):
            note_env(node.args[0],
                     "set" if node.func.attr == "setdefault" else "read")

    for node in mod.walk(ast.Subscript):
        direction = "set" if isinstance(node.ctx, ast.Store) else "read"
        note_header(node.slice, direction)
        note_env(node.slice, direction)

    for node in mod.walk(ast.Dict):
        for key in node.keys:
            if key is None:
                continue
            note_header(key, "set")
            note_env(key, "set")

    # The middlebox forward-list: a module-level *_FORWARD*_ tuple of
    # header names (core/headers.FORWARD_HEADERS).
    for stmt in mod.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and "FORWARD" in stmt.targets[0].id:
            got = _str_consts(mod).get(stmt.targets[0].id)
            if isinstance(got, tuple) and got \
                    and all(_HEADER_RE.match(h) for h in got):
                out["forward_list"] = (got, stmt)

    # Status fields: produced = literal dict keys and literal-key
    # subscript stores anywhere; consumed = .get()/[] on a variable
    # assigned from json.loads, in the same function.
    for node in mod.walk(ast.Dict):
        for key in node.keys:
            if isinstance(key, ast.Constant) and isinstance(key.value, str):
                out["fields_produced"].add(key.value)
    for node in mod.walk(ast.Subscript):
        if isinstance(node.ctx, ast.Store) \
                and isinstance(node.slice, ast.Constant) \
                and isinstance(node.slice.value, str):
            out["fields_produced"].add(node.slice.value)

    for fn in mod.walk(ast.FunctionDef, ast.AsyncFunctionDef):
        json_vars = set()
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name) \
                    and isinstance(sub.value, ast.Call) \
                    and mod.qualname(sub.value.func) == "json.loads":
                json_vars.add(sub.targets[0].id)
        if not json_vars:
            continue
        for sub in ast.walk(fn):
            if not isinstance(sub, ast.Call) or not sub.args \
                    or not isinstance(sub.func, ast.Attribute) \
                    or sub.func.attr != "get" \
                    or not isinstance(sub.func.value, ast.Name) \
                    or sub.func.value.id not in json_vars:
                continue
            arg = sub.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                out["fields_consumed"].append((arg.value, sub))
            elif isinstance(arg, ast.Name):
                for fill in _loop_fills(fn, arg.id, sub) or ():
                    out["fields_consumed"].append((fill, sub))
    return out


# -- whole-program aggregation -------------------------------------------------


def _table(mod: Module) -> dict:
    """The aggregated contract table the rules read: program-wide when a
    ``Program`` is attached, module-local otherwise."""
    if mod.program is not None:
        return mod.program.memo("xcontract_table",
                                lambda p: _aggregate(p.modules, p))
    return _aggregate([mod], None)


def _aggregate(modules: Iterable[Module], program: Optional[Program]) -> dict:
    t = {
        "series_produced": {},    # name -> [(mod, node, is_hist)]
        "series_hist": set(),
        "series_prefix": [],      # (prefix, mod, node)
        "series_consumed": {},    # name -> [(mod, node)]
        "headers_set": {},        # lower -> [(spelling, mod, node)]
        "headers_read": {},
        "forward_lists": [],      # (names, mod, node)
        "env_set": {},            # name -> [(mod, node)]
        "env_read": {},
        "fields_produced": set(),
        "fields_consumed": {},    # name -> [(mod, node)]
    }
    for mod in modules:
        ex = _extract(mod)
        for name, node, exact, is_hist in ex["series_produced"]:
            t["series_produced"].setdefault(name, []).append(
                (mod, node, is_hist))
            if is_hist:
                t["series_hist"].add(name)
        for prefix, node in ex["series_prefix"]:
            t["series_prefix"].append((prefix, mod, node))
        for name, node in ex["series_consumed"]:
            t["series_consumed"].setdefault(name, []).append((mod, node))
        for direction in ("set", "read"):
            for spelling, node in ex[f"headers_{direction}"]:
                t[f"headers_{direction}"].setdefault(
                    spelling.lower(), []).append((spelling, mod, node))
        for qual, direction, node in ex["headers_pending"]:
            value = _resolve_pending(program, qual)
            if value is not None and _HEADER_RE.match(value):
                t[f"headers_{direction}"].setdefault(
                    value.lower(), []).append((value, mod, node))
        if ex["forward_list"] is not None:
            names, node = ex["forward_list"]
            t["forward_lists"].append((names, mod, node))
        for direction in ("set", "read"):
            for name, node in ex[f"env_{direction}"]:
                t[f"env_{direction}"].setdefault(name, []).append(
                    (mod, node))
        for qual, direction, node in ex["env_pending"]:
            value = _resolve_pending(program, qual)
            if value is not None and _ENV_RE.match(value):
                t[f"env_{direction}"].setdefault(value, []).append(
                    (mod, node))
        t["fields_produced"] |= ex["fields_produced"]
        for name, node in ex["fields_consumed"]:
            t["fields_consumed"].setdefault(name, []).append((mod, node))
    return t


def _series_produced_match(t: dict, name: str) -> bool:
    if name in t["series_produced"]:
        return True
    base = series_base(name)
    if base != name and base in t["series_hist"]:
        return True
    return any(name.startswith(prefix) and name != prefix
               for prefix, _, _ in t["series_prefix"])


def _series_consumed_match(t: dict, name: str, is_hist: bool) -> bool:
    if name in t["series_consumed"]:
        return True
    if is_hist:
        return any(name + suffix in t["series_consumed"]
                   for suffix in HIST_SUFFIXES)
    return False


# -- README metric catalog (the X702 documented set) ---------------------------


_docs_cache: Optional[tuple[str, frozenset]] = None


def documented_series(root: Optional[str] = None) -> frozenset:
    """Every ``kftpu_*`` token in the repo README — the metric catalog.
    A produced series nobody consumes in-scan is still contract-clean
    when the README documents it (dashboards and operators are consumers
    the AST cannot see). Cached per README path."""
    global _docs_cache
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    candidates = [os.path.join(root, "README.md"),
                  os.path.join(os.getcwd(), "README.md")]
    path = next((c for c in candidates if os.path.isfile(c)), None)
    if path is None:
        return frozenset()
    if _docs_cache is not None and _docs_cache[0] == path:
        return _docs_cache[1]
    try:
        with open(path, encoding="utf-8") as f:
            names = frozenset(re.findall(r"kftpu_[a-z0-9_]+", f.read()))
    except OSError:
        names = frozenset()
    _docs_cache = (path, names)
    return names


def _documented(name: str, docs: frozenset) -> bool:
    return name in docs or series_base(name) in docs \
        or any(name + s in docs for s in HIST_SUFFIXES)


# -- the rules -----------------------------------------------------------------


def _escaped(mod: Module, node: ast.AST) -> bool:
    return mod.annotation(node, "contract") is not None


def _test_module(mod: Module) -> bool:
    """Test modules CONTRIBUTE to the contract table (a test scraping a
    series is a real consumer when it is in the linted set) but are never
    REPORTED on: a test asserting on a stale name fails loudly at test
    time — the opposite of the silent drift the X-rules exist for — and
    fixture name-literals inside tests would otherwise be findings every
    time ``--changed`` touches a test file."""
    parts = mod.relpath.split("/")
    return "tests" in parts or parts[-1].startswith(("test_", "conftest"))


@register
class ConsumedSeriesNeverProduced(Rule):
    id = "X701"
    name = "consumed-series-never-produced"
    doc = ("a literal metric-series name is scraped/compared against "
           "exposition output but no registry definition site produces "
           "it — the renamed-producer half of autoscaler blindness")

    def check(self, mod: Module) -> Iterable:
        if mod.program is None:
            return    # cross-component: needs the other components
        if _test_module(mod):
            return    # tests contribute sites, never findings
        t = _table(mod)
        for name, node in _extract(mod)["series_consumed"]:
            if _escaped(mod, node):
                continue
            if _series_produced_match(t, name):
                continue
            yield mod.finding(
                self, node,
                f"series {name!r} is consumed here but nothing in the "
                "program produces it (no registry definition site, loop "
                "expansion, or dynamic prefix matches) — renamed "
                "producer or typo")


@register
class ProducedSeriesUnconsumed(Rule):
    id = "X702"
    name = "produced-series-unconsumed-undocumented"
    doc = ("an exact metric-series name is registered but neither "
           "consumed anywhere in the scan set nor documented in the "
           "README metric catalog — dead telemetry or a renamed "
           "consumer")

    def check(self, mod: Module) -> Iterable:
        if mod.program is None:
            return    # cross-component: needs the other components
        if _test_module(mod):
            return    # tests contribute sites, never findings
        t = _table(mod)
        docs = documented_series()
        seen: set[tuple] = set()
        for name, node, exact, is_hist in _extract(mod)["series_produced"]:
            key = (name, id(node))
            if key in seen:      # loop-expanded duplicates: one site each
                continue
            seen.add(key)
            if _escaped(mod, node):
                continue
            if _series_consumed_match(t, name, is_hist):
                continue
            if _documented(name, docs):
                continue
            yield mod.finding(
                self, node,
                f"series {name!r} is produced but never consumed in the "
                "scan set and absent from the README metric catalog — "
                "document it (or annotate '# contract: <reason>') so a "
                "renamed consumer cannot go unnoticed")


@register
class HeaderContractDrift(Rule):
    id = "X703"
    name = "header-contract-drift"
    doc = ("an X-Kftpu-* header read that nothing sets (typo/stale "
           "consumer), set that nothing reads, case-drifting spellings, "
           "or a serving-path header missing from the middlebox "
           "forward-list")

    def check(self, mod: Module) -> Iterable:
        if mod.program is None:
            return    # cross-component: needs the other components
        if _test_module(mod):
            return    # tests contribute sites, never findings
        t = _table(mod)
        ex = _extract(mod)

        def sites(direction):
            for spelling, node in ex[f"headers_{direction}"]:
                yield spelling, node
            for qual, d, node in ex["headers_pending"]:
                if d != direction:
                    continue
                value = _resolve_pending(mod.program, qual)
                if value is not None and _HEADER_RE.match(value):
                    yield value, node

        for spelling, node in sites("read"):
            if _escaped(mod, node):
                continue
            if spelling.lower() not in t["headers_set"]:
                yield mod.finding(
                    self, node,
                    f"header {spelling!r} is read here but nothing in "
                    "the program sets it — typo, case drift, or a "
                    "renamed producer")
        for spelling, node in sites("set"):
            if _escaped(mod, node):
                continue
            if spelling.lower() not in t["headers_read"]:
                yield mod.finding(
                    self, node,
                    f"header {spelling!r} is set here but nothing in "
                    "the program reads it — dead header or a renamed "
                    "consumer")
        # Case drift: every spelling must match the program's canonical
        # (most frequent) one — HTTP is case-insensitive but the literal
        # dict lookups around it are not.
        spell_counts: dict[str, dict] = {}
        for d in ("set", "read"):
            for lower, entries in t[f"headers_{d}"].items():
                counts = spell_counts.setdefault(lower, {})
                for spelling, _, _ in entries:
                    counts[spelling] = counts.get(spelling, 0) + 1
        for direction in ("read", "set"):
            for spelling, node in sites(direction):
                counts = spell_counts.get(spelling.lower(), {})
                if len(counts) < 2 or _escaped(mod, node):
                    continue
                canonical = max(sorted(counts), key=counts.get)
                if spelling != canonical:
                    yield mod.finding(
                        self, node,
                        f"header spelled {spelling!r} here but "
                        f"{canonical!r} elsewhere — case/spelling drift")
        # Forward-list: every header exchanged on the serving path must
        # ride through the chaos middlebox (finding lands on the list's
        # owning module).
        for names, fmod, fnode in t["forward_lists"]:
            if fmod is not mod or _escaped(mod, fnode):
                continue
            fwd = {n.lower() for n in names}
            for lower in sorted(set(t["headers_set"]) & set(
                    t["headers_read"])):
                if lower in fwd:
                    continue
                on_serving_path = any(
                    "serve/" in m.relpath
                    for _, m, _ in (t["headers_set"][lower]
                                    + t["headers_read"][lower]))
                if not on_serving_path:
                    continue
                spelling = t["headers_set"][lower][0][0]
                yield mod.finding(
                    self, fnode,
                    f"serving-path header {spelling!r} is missing from "
                    "the middlebox forward-list — a proxy in the path "
                    "would silently strip it")


@register
class OrphanEnvVar(Rule):
    id = "X704"
    name = "orphan-env-var"
    doc = ("a KFTPU_* env var read that nothing writes into a child "
           "environment, or written but never read — the rendezvous-"
           "boundary rename (annotate '# contract:' for user-facing "
           "knobs)")

    def check(self, mod: Module) -> Iterable:
        if mod.program is None:
            return    # cross-component: needs the other components
        if _test_module(mod):
            return    # tests contribute sites, never findings
        t = _table(mod)
        ex = _extract(mod)

        def sites(direction):
            for name, node in ex[f"env_{direction}"]:
                yield name, node
            for qual, d, node in ex["env_pending"]:
                if d != direction:
                    continue
                value = _resolve_pending(mod.program, qual)
                if value is not None and _ENV_RE.match(value):
                    yield value, node

        for name, node in sites("read"):
            if _escaped(mod, node):
                continue
            if name not in t["env_set"]:
                yield mod.finding(
                    self, node,
                    f"env var {name!r} is read here but nothing in the "
                    "program writes it — renamed producer, or a user "
                    "knob that needs a '# contract:' reason")
        for name, node in sites("set"):
            if _escaped(mod, node):
                continue
            if name not in t["env_read"]:
                yield mod.finding(
                    self, node,
                    f"env var {name!r} is written here but nothing in "
                    "the program reads it — renamed consumer, or an "
                    "export for out-of-scan code that needs a "
                    "'# contract:' reason")


@register
class StatusFieldDrift(Rule):
    id = "X705"
    name = "status-field-drift"
    doc = ("a JSON field name read off a parsed record (json.loads → "
           ".get) that no writer produces as a literal dict key — the "
           "metrics.jsonl/status scrape boundary rename")

    def check(self, mod: Module) -> Iterable:
        if mod.program is None:
            return    # cross-component: needs the other components
        if _test_module(mod):
            return    # tests contribute sites, never findings
        t = _table(mod)
        for name, node in _extract(mod)["fields_consumed"]:
            if _escaped(mod, node):
                continue
            if name in t["fields_produced"]:
                continue
            yield mod.finding(
                self, node,
                f"field {name!r} is read off a parsed JSON record but "
                "no writer in the program produces it as a dict key — "
                "renamed writer or typo")


# -- the manifest (--contracts-json / the runtime auditor's reference) ---------


def contract_manifest(program: Program) -> dict:
    """Serialize the whole-program contract table: the
    ``kftpu lint --contracts-json`` document. Sites render as
    ``path:line`` so drift reports are clickable; the runtime contract
    auditor (``KFTPU_SANITIZE=contract``) diffs observed exchanges
    against the name lists."""
    t = program.memo("xcontract_table",
                     lambda p: _aggregate(p.modules, p))

    def site(mod: Module, node: ast.AST) -> str:
        return f"{mod.relpath}:{getattr(node, 'lineno', 0)}"

    def named_sites(d: dict) -> dict:
        return {key: sorted({site(m, n) for m, n in entries})
                for key, entries in sorted(d.items())}

    series_produced = {}
    for name, entries in sorted(t["series_produced"].items()):
        series_produced[name] = sorted({site(m, n) for m, n, _ in entries})
    headers = {
        "set": {},
        "read": {},
        "forward_list": sorted({n for names, _, _ in t["forward_lists"]
                                for n in names}),
    }
    for direction in ("set", "read"):
        for lower, entries in sorted(t[f"headers_{direction}"].items()):
            spelling = entries[0][0]
            headers[direction][spelling] = sorted(
                {site(m, n) for _, m, n in entries})
    return {
        "version": 1,
        "series": {
            "produced": series_produced,
            "produced_prefixes": sorted(
                {p for p, _, _ in t["series_prefix"]}),
            "histograms": sorted(t["series_hist"]),
            "consumed": named_sites(t["series_consumed"]),
        },
        "headers": headers,
        "env": {
            "set": named_sites(t["env_set"]),
            "read": named_sites(t["env_read"]),
        },
        "fields": {
            "produced": sorted(t["fields_produced"]),
            "consumed": named_sites(t["fields_consumed"]),
        },
    }
