"""Metric-definition-site rules — ``obs/registry.lint()`` made static.

The runtime registry already refuses duplicate families and the obs smoke
stage lints the ``kftpu_`` prefix at render time; these rules move both
checks to the definition site so a bad metric name fails ``kftpu lint``
instead of the first scrape:

- M201 ``metric-name``: a literal name passed to ``.counter()`` /
  ``.gauge()`` / ``.histogram()`` (or a ``Counter``/``Gauge``/
  ``Histogram`` constructor imported from ``obs.registry``) must carry
  the ``kftpu_`` prefix and match the exposition grammar. f-strings are
  checked on their literal head.
- M202 ``duplicate-metric``: the same literal name registered twice in
  one function (two families with one name — the registry would raise at
  runtime; the lint catches it before).
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Optional

from kubeflow_tpu.analysis.core import Finding, Module, Rule, register

_PREFIX = "kftpu_"
_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_REG_METHODS = {"counter", "gauge", "histogram"}
_REG_CLASSES = {
    "kubeflow_tpu.obs.registry.Counter",
    "kubeflow_tpu.obs.registry.Gauge",
    "kubeflow_tpu.obs.registry.Histogram",
}


def _literal_name(node: ast.AST) -> tuple[Optional[str], bool]:
    """(name, exact): the literal metric name, and whether it is complete
    (False for f-strings, where only the head is known)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value, True
    if isinstance(node, ast.JoinedStr) and node.values:
        head = node.values[0]
        if isinstance(head, ast.Constant) and isinstance(head.value, str):
            return head.value, False
        return None, False
    return None, True


def _definition_sites(mod: Module) -> Iterable[tuple[ast.Call, str, bool]]:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        is_site = False
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _REG_METHODS:
            is_site = True
        elif mod.qualname(node.func) in _REG_CLASSES:
            is_site = True
        if not is_site:
            continue
        name, exact = _literal_name(node.args[0])
        if name is None:
            continue
        yield node, name, exact


@register
class MetricName(Rule):
    id = "M201"
    name = "metric-name"
    doc = (f"metric family name must carry the '{_PREFIX}' prefix and "
           "match the exposition grammar (obs/registry.lint(), static)")

    def check(self, mod: Module) -> Iterable[Finding]:
        for node, name, exact in _definition_sites(mod):
            if not name.startswith(_PREFIX):
                yield mod.finding(
                    self, node,
                    f"metric name {name!r} is missing the platform "
                    f"prefix {_PREFIX!r}")
            elif exact and not _NAME_RE.match(name):
                yield mod.finding(
                    self, node,
                    f"metric name {name!r} is not a valid exposition "
                    "metric name")


@register
class DuplicateMetric(Rule):
    id = "M202"
    name = "duplicate-metric"
    doc = ("the same literal metric name registered twice in one "
           "function (duplicate family — the registry raises at scrape "
           "time; fail at lint time instead)")

    def check(self, mod: Module) -> Iterable[Finding]:
        per_fn: dict[int, dict[str, ast.Call]] = {}
        for node, name, exact in _definition_sites(mod):
            if not exact:
                continue
            fn = mod.enclosing_function(node)
            key = id(fn) if fn is not None else 0
            seen = per_fn.setdefault(key, {})
            if name in seen:
                yield mod.finding(
                    self, node,
                    f"metric name {name!r} registered twice in the same "
                    "function; two families cannot share a name")
            else:
                seen[name] = node
