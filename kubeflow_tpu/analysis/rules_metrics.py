"""Metric-definition-site rules — ``obs/registry.lint()`` made static.

The runtime registry already refuses duplicate families and the obs smoke
stage lints the ``kftpu_`` prefix at render time; these rules move both
checks to the definition site so a bad metric name fails ``kftpu lint``
instead of the first scrape:

- M201 ``metric-name``: a literal name passed to ``.counter()`` /
  ``.gauge()`` / ``.histogram()`` (or a ``Counter``/``Gauge``/
  ``Histogram`` constructor imported from ``obs.registry``) must carry
  the ``kftpu_`` prefix and match the exposition grammar. An f-string
  whose only hole is a variable of an enclosing LITERAL ``for`` loop
  (the PR-6 ``f"kftpu_serving_{k}"`` labeled-series idiom) expands to
  every name it can take and each is checked in full; other f-strings
  are checked on their literal head.
- M202 ``duplicate-metric``: the same literal name registered twice in
  one function (two families with one name — the registry would raise at
  runtime; the lint catches it before), loop-expanded names included.
- M203 ``bad-series-label``: reserved (``le``/``quantile``) or
  malformed label names at the labeled-series sample sites
  (``.inc()``/``.set()``/``.observe()``/``.set_cumulative()`` keywords
  and literal ``**{...}`` splats) — the qos/model label surface PR 6
  introduced, checked where the labels are written.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Optional

from kubeflow_tpu.analysis.core import Finding, Module, Rule, register

_PREFIX = "kftpu_"
_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_REG_METHODS = {"counter", "gauge", "histogram"}
_REG_CLASSES = {
    "kubeflow_tpu.obs.registry.Counter",
    "kubeflow_tpu.obs.registry.Gauge",
    "kubeflow_tpu.obs.registry.Histogram",
}


def _loop_literals(node: ast.AST, var: str) -> Optional[list[str]]:
    """The literal string values ``var`` iterates over in an enclosing
    ``for var in ("a", "b", ...)`` loop, else None."""
    cur = getattr(node, "_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            return None
        if isinstance(cur, ast.For) and isinstance(cur.target, ast.Name) \
                and cur.target.id == var \
                and isinstance(cur.iter, (ast.Tuple, ast.List, ast.Set)):
            vals = [e.value for e in cur.iter.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)]
            if len(vals) == len(cur.iter.elts):
                return vals
        cur = getattr(cur, "_parent", None)
    return None


def _literal_names(node: ast.AST) -> list[tuple[str, bool]]:
    """[(name, exact)] for the metric-name argument. Plain strings are one
    exact name. An f-string whose ONLY interpolation is a variable bound
    by an enclosing literal ``for`` loop expands to every name it can
    take (all exact — the PR-6 ``f"kftpu_serving_{k}"`` labeled-series
    pattern, checked in full). Any other f-string contributes its literal
    head, inexact (prefix check only)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [(node.value, True)]
    if isinstance(node, ast.JoinedStr) and node.values:
        holes = [v for v in node.values
                 if isinstance(v, ast.FormattedValue)]
        if len(holes) == 1 and isinstance(holes[0].value, ast.Name):
            fills = _loop_literals(node, holes[0].value.id)
            if fills is not None:
                out = []
                for fill in fills:
                    parts = []
                    for v in node.values:
                        if isinstance(v, ast.Constant):
                            parts.append(str(v.value))
                        else:
                            parts.append(fill)
                    out.append(("".join(parts), True))
                return out
        head = node.values[0]
        if isinstance(head, ast.Constant) and isinstance(head.value, str):
            return [(head.value, False)]
        return []
    return []


def _definition_sites(mod: Module) -> Iterable[tuple[ast.Call, str, bool]]:
    for node in mod.walk():
        if not isinstance(node, ast.Call) or not node.args:
            continue
        is_site = False
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _REG_METHODS:
            is_site = True
        elif mod.qualname(node.func) in _REG_CLASSES:
            is_site = True
        if not is_site:
            continue
        for name, exact in _literal_names(node.args[0]):
            yield node, name, exact


@register
class MetricName(Rule):
    id = "M201"
    name = "metric-name"
    doc = (f"metric family name must carry the '{_PREFIX}' prefix and "
           "match the exposition grammar (obs/registry.lint(), static)")

    def check(self, mod: Module) -> Iterable[Finding]:
        for node, name, exact in _definition_sites(mod):
            if not name.startswith(_PREFIX):
                yield mod.finding(
                    self, node,
                    f"metric name {name!r} is missing the platform "
                    f"prefix {_PREFIX!r}")
            elif exact and not _NAME_RE.match(name):
                yield mod.finding(
                    self, node,
                    f"metric name {name!r} is not a valid exposition "
                    "metric name")


_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_RESERVED_LABELS = {"le", "quantile", "__name__"}
_LABELED_METHODS = {"inc", "set", "observe", "set_cumulative"}


@register
class BadSeriesLabel(Rule):
    id = "M203"
    name = "bad-series-label"
    doc = ("reserved or malformed label name at a labeled-series sample "
           "site (.inc/.set/.observe(..., label=...)): 'le'/'quantile' "
           "are exposition-reserved, dict-splat keys must match the "
           "label grammar")

    def check(self, mod: Module) -> Iterable[Finding]:
        handles = self._metric_handles(mod)
        for node in mod.walk():
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute) \
                    or node.func.attr not in _LABELED_METHODS:
                continue
            recv = node.func.value
            is_handle = (
                (isinstance(recv, ast.Name) and recv.id in handles)
                or (isinstance(recv, ast.Call)
                    and isinstance(recv.func, ast.Attribute)
                    and recv.func.attr in _REG_METHODS))
            if not is_handle:
                continue
            for kw in node.keywords:
                if kw.arg is None:
                    # **{...} splat: literal dict keys are checkable
                    if isinstance(kw.value, ast.Dict):
                        for k in kw.value.keys:
                            if isinstance(k, ast.Constant) \
                                    and isinstance(k.value, str) \
                                    and (k.value in _RESERVED_LABELS
                                         or not _LABEL_RE.match(k.value)):
                                yield mod.finding(
                                    self, node,
                                    f"label name {k.value!r} is "
                                    "reserved or not a valid exposition "
                                    "label")
                elif kw.arg in _RESERVED_LABELS:
                    yield mod.finding(
                        self, node,
                        f"label name {kw.arg!r} is reserved by the "
                        "exposition format (histogram/summary internals)")

    @staticmethod
    def _metric_handles(mod: Module) -> set[str]:
        """Local names bound from ``reg.counter(...)``-style calls —
        the codebase's labeled-series definition idiom."""
        out: set[str] = set()
        for node in mod.walk():
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and isinstance(node.value.func, ast.Attribute) \
                    and node.value.func.attr in _REG_METHODS:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
        return out


@register
class DuplicateMetric(Rule):
    id = "M202"
    name = "duplicate-metric"
    doc = ("the same literal metric name registered twice in one "
           "function (duplicate family — the registry raises at scrape "
           "time; fail at lint time instead)")

    def check(self, mod: Module) -> Iterable[Finding]:
        per_fn: dict[int, dict[str, ast.Call]] = {}
        for node, name, exact in _definition_sites(mod):
            if not exact:
                continue
            fn = mod.enclosing_function(node)
            key = id(fn) if fn is not None else 0
            seen = per_fn.setdefault(key, {})
            if name in seen:
                yield mod.finding(
                    self, node,
                    f"metric name {name!r} registered twice in the same "
                    "function; two families cannot share a name")
            else:
                seen[name] = node
