"""Tiered KV cache: radix prefix index with copy-on-write page sharing +
host-RAM overflow tier with async device↔host page migration (ROADMAP
item 1 — the cross-request prefix-caching layer between the scheduler
and the page pool).

Why the flat hash wasn't enough: ``PageAllocator``'s chained full-prompt
hash only ever matches FULL pages of a prompt registered at prefill
completion, keys cached content by exact block chains, and loses every
decode-grown token at slot release — so an idle conversation re-arriving
(the dominant shape at millions-of-users traffic: same system prompt,
same history, one new turn) prefills almost everything again, while its
dead pages pin HBM until LRU eviction destroys exactly the content the
next turn needed.

**Radix prefix index.** One tree over the paged pool; a node is one
page-sized token block (partial leaves hold the sub-page tail of a
registered sequence). ``match_and_acquire`` walks the query and returns
the longest shared path — WHILE the original owner is still decoding
(live sharing: node pages carry one allocator ref per sharer, so
``KFTPU_SANITIZE=refcount`` attributes every reference to its request
and ``assert_quiescent`` stays exact per owner). Divergence inside a
block is copy-on-write: the new request gets a fresh page and ONE device
dispatch copies only the shared partial tail (serve/paged.copy_pages);
prefill then resumes mid-page (the per-token scatter in
``paged_chunk_prefill`` removed the page-alignment restriction). Shared
pages are never written: decode and chunk writes always land at
positions past the claimed content, and the partial tail is privately
owned after the copy — COW by construction, enforced rather than
checked. Registration happens at prefill completion (prompt blocks,
live), at slot release (prompt + generated tokens — conversations
survive), at chunking preemption, and at handoff adoption.

**Ownership model** (extends, never replaces, the allocator's): the
tree itself holds NO references. A node page's refcount is exactly its
sharer count; at ref==0 the page parks on the allocator's reclaimable
LRU (``PageAllocator.retained`` keeps it there without a flat-hash
key), still indexed and matchable. Pool pressure evicts reclaimable
pages LRU as before; the ``on_evict`` callback drops the node and
cascades its now-unreachable subtree back to the free list (a
descendant of a ref-0 page is provably ref-0 itself: any sharer of a
deep node holds references to every ancestor on its path).

**Host-RAM overflow tier.** Cold prefix subtrees — sharer-free device
pages idle past ``demote_after_s`` — migrate device→host in batches:
the scheduler enqueues ONE device-side gather per batch (program order
makes the immediate page free safe, exactly like the handoff export)
and the background migration thread does the blocking ``device_get``
plus the wire encode (``serve/handoff.pages_to_wire`` — the same
JSON-meta + raw little-endian byte layout the handoff POST ships), so
the scheduler never blocks on a demotion. A radix hit on a host node
promotes BEFORE prefill admits: decode the blob (zero-copy
``frombuffer``), allocate device pages, and enqueue one batched upload
— JAX program order guarantees the subsequent chunk prefill's gather
reads the promoted content, so admission proceeds the same step with
no wait state. Long-idle conversations stop pinning HBM and still skip
their recompute.

**Remote-storage third tier (ISSUE 17 — the fleet property).** Host
blobs idle past ``remote_after_s`` spill PAST host RAM into the
artifact store (pipelines/artifacts.py — content-addressed, so a
blob's digest IS its checksum): the migration thread publishes the
already-encoded wire blob and registers it under a name derived from
``(fabric signature, namespace, block chain)``, so ANY replica serving
the same model shape finds it by walking its own radix miss — a
conversation's KV now survives its engine. A walk that runs out of
in-memory nodes probes that registry for the next block; remote work
is DEADLINE-BOUNDED per match (``remote_deadline_s``): a slow or
unreachable store degrades to a shorter match (= recompute of the
tail), surfaced in ``remote_promote_timeouts``, and never wedges
admission. Fetched bytes are re-verified against the content address
before a page is allocated — a truncated or corrupt blob is a miss
plus ``remote_blobs_corrupt``, never corrupted pages. Crash ordering
is publish→register→install: a SIGKILL mid-spill leaves at worst an
UNREGISTERED blob, which the store's GC sweep (pipelines/gc.py)
reconciles — zero orphans after the sweep, by construction.
"""

from __future__ import annotations

import hashlib
import logging
import queue
import threading
import time
from typing import Callable, Optional, Sequence

import numpy as np

from kubeflow_tpu.serve.handoff import pages_from_wire, pages_to_wire
from kubeflow_tpu.serve.retry import STORE_POLICY, call_with_retry, env_float

logger = logging.getLogger("kubeflow_tpu.serve.kvtier")

TIER_DEVICE = "device"
TIER_HOST = "host"
TIER_MIGRATING = "migrating"   # gather enqueued, blob not installed yet
TIER_DEAD = "dead"             # evicted; structure detached
TIER_SPILLING = "spilling"     # host blob, remote publish in flight —
                               # still matchable exactly like TIER_HOST
TIER_REMOTE = "remote"         # blob field holds the cas:// uri

#: Content-address scheme of the artifact store — a TIER_REMOTE node's
#: ``blob`` is ``cas://<sha256hex>``; the hex part is the checksum the
#: promote path re-verifies fetched bytes against.
_CAS = "cas://"

#: Partial (sub-page) leaves kept per parent: enough to hold a few
#: divergent continuations of one prefix without making the tail scan a
#: per-admission hot spot.
MAX_PARTIALS = 4


class _Node:
    """One page-sized token block. ``block`` is the claimed content
    (len == page_size for full blocks; shorter for partial leaves —
    positions past ``len(block)`` in the page are unclaimed). Exactly one
    of: ``page`` set (device/migrating) or ``blob`` set (host)."""

    __slots__ = ("block", "page", "tier", "blob", "children", "partials",
                 "parent", "last_used")

    def __init__(self, block: tuple, page: Optional[int], parent):
        self.block = block
        self.page = page
        self.tier = TIER_DEVICE
        self.blob: Optional[bytes] = None
        self.children: dict = {}     # full-block tuple -> _Node
        self.partials: list = []     # sub-page leaves
        self.parent = parent
        self.last_used = time.monotonic()

    def full(self, page_size: int) -> bool:
        return len(self.block) == page_size


def _lcp(a: Sequence[int], b: Sequence[int]) -> int:
    n = min(len(a), len(b))
    i = 0
    while i < n and a[i] == b[i]:
        i += 1
    return i


class RadixPrefixIndex:
    """Radix tree + tier lifecycle over one ``PageAllocator``.

    Tree structure (children/partials/by_page) and every public method
    are SCHEDULER-CONFINED — the engine calls them from its scheduler
    thread only, like the allocator itself. The one cross-thread seam is
    the migration thread installing host blobs; ``_lock`` guards the
    tier/blob/host-count transitions it shares with the scheduler.

    Device operations are injected as closures (the engine owns the
    cache pytree and its jitted programs):

    - ``copy_pages_fn(src_ids, dst_ids)`` — pool page copy (COW tails);
    - ``upload_pages_fn(page_ids, k, v)`` — host→device promotion
      (``k``/``v`` are ``[L, n, pg, KV, Dh]`` numpy);
    - ``fetch_pages_fn(page_ids)`` — device-side gather returning device
      arrays (the demotion batch; the migration thread device_gets them).
    """

    def __init__(self, allocator, page_size: int, *,
                 host_pages: int = 0,
                 demote_after_s: float = 2.0,
                 migrate_batch_pages: int = 32,
                 scan_interval_s: Optional[float] = None,
                 copy_pages_fn: Optional[Callable] = None,
                 upload_pages_fn: Optional[Callable] = None,
                 fetch_pages_fn: Optional[Callable] = None,
                 pressure_fn: Optional[Callable[[], float]] = None,
                 remote_store=None,
                 remote_after_s: Optional[float] = None,
                 remote_deadline_s: Optional[float] = None,
                 fabric_sig: str = ""):
        self._allocator = allocator
        self.page_size = int(page_size)
        self.host_pages = max(0, int(host_pages))
        self.demote_after_s = float(demote_after_s)
        self.migrate_batch_pages = max(1, int(migrate_batch_pages))
        # Remote third tier (None = off): an ArtifactStore-shaped object
        # (put_bytes/get_bytes/register/lookup). ``fabric_sig`` folds the
        # cache geometry + dtype into every registry key so replicas of
        # DIFFERENT model shapes can share one store root without ever
        # adopting each other's pages.
        self._remote_store = remote_store
        self.remote_after_s = (float(remote_after_s)
                               if remote_after_s is not None
                               else 2.0 * self.demote_after_s)
        self.remote_deadline_s = (float(remote_deadline_s)
                                  if remote_deadline_s is not None
                                  else env_float("KFTPU_KV_REMOTE_DEADLINE_S",
                                                 0.5))
        self.fabric_sig = str(fabric_sig)
        self._scan_interval = (float(scan_interval_s)
                               if scan_interval_s is not None
                               else max(self.demote_after_s / 4, 0.05))
        self._copy_pages = copy_pages_fn
        self._upload_pages = upload_pages_fn
        self._fetch_pages = fetch_pages_fn
        # Demotion-urgency signal (ROADMAP item 1 remaining upside → the
        # ISSUE 14 en-passant fix): a callable returning a pressure ratio
        # — >= 1.0 means "memory is about to be reclaimed destructively,
        # demote NOW even while foreground work runs". The engine folds
        # pool occupancy, its queue-delay-vs-budget ratio (the SAME
        # signal the SLO autoscaler scrapes off /metrics), and adapter
        # hot-load backpressure into it, so KV demotion and adapter
        # loads stop fighting over the same HBM headroom under pressure.
        # None = the classic pool-occupancy-only rule.
        self._pressure_fn = pressure_fn
        self._roots: dict[str, _Node] = {"": _Node((), None, None)}
        self._by_page: dict[int, _Node] = {}  # lockfree: scheduler-confined
        # Tier transitions + host accounting cross the migration-thread
        # seam; everything below shares one reentrant lock (reentrant:
        # an alloc inside match can fire on_evict back into the index).
        self._lock = threading.RLock()
        self._host_count = 0          # guarded_by: _lock
        self._migrating = 0           # guarded_by: _lock
        self._remote_count = 0        # guarded_by: _lock
        self._spilling = 0            # guarded_by: _lock
        self.stats = {                # guarded_by: _lock
            "prefix_queries": 0, "prefix_hits": 0,
            "tokens_matched": 0, "tokens_cow": 0,
            "cow_copies": 0, "nodes": 0,
            "pages_demoted": 0, "pages_promoted": 0,
            "demote_batches": 0, "demote_dropped": 0,
            "host_evictions": 0, "evictions": 0,
            "demote_wire_bytes": 0, "promote_wire_bytes": 0,
            # Remote third tier: spill (host→store) / remote promote
            # (store→device) traffic plus every degrade path, each with
            # its own counter so attribution names the faulted phase.
            "pages_demoted_remote": 0, "pages_promoted_remote": 0,
            "remote_demote_bytes": 0, "remote_promote_bytes": 0,
            "remote_promote_timeouts": 0, "remote_promote_errors": 0,
            "remote_blobs_corrupt": 0, "remote_registry_hits": 0,
            "remote_spill_errors": 0, "remote_spill_dropped": 0,
        }
        self._last_scan = 0.0         # lockfree: scheduler-confined
        self.last_promoted = 0        # lockfree: scheduler-confined
        self.last_cow_tokens = 0      # lockfree: scheduler-confined
        self._queue: "queue.Queue" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        allocator.on_evict = self._on_evict
        if self.host_pages > 0 or self._remote_store is not None:
            self._thread = threading.Thread(
                target=self._migrate_loop, daemon=True, name="kv-migrate")
            self._thread.start()

    # -- observability -------------------------------------------------------

    def pressure(self) -> float:
        """Current demotion-urgency ratio (>= 1.0 = urgent). The default
        reproduces the classic rule exactly: pressure hits 1.0 when
        free+cached pages fall to a quarter of the pool."""
        if self._pressure_fn is not None:
            return float(self._pressure_fn())
        quarter = self._allocator.num_pages // 4
        return quarter / max(self._allocator.available(), 1)

    def host_pages_resident(self) -> int:
        with self._lock:
            return self._host_count

    def remote_pages_resident(self) -> int:
        with self._lock:
            return self._remote_count

    def snapshot(self) -> dict:
        with self._lock:
            out = dict(self.stats)
            out["host_pages_resident"] = self._host_count
            out["migrating_pages"] = self._migrating
            out["remote_pages_resident"] = self._remote_count
            out["spilling_pages"] = self._spilling
        return out

    # -- match (admission path) ----------------------------------------------

    def root(self, namespace: str = "") -> _Node:
        """The radix root of one KV namespace. Multi-tenant LoRA serving
        namespaces the index per adapter (serve/lora.py): KV content is a
        function of (tokens, model variant), so the same prompt under two
        adapters must never share pages — separate roots make the
        isolation structural rather than checked."""
        node = self._roots.get(namespace)
        if node is None:
            node = self._roots[namespace] = _Node((), None, None)
        return node

    def match_and_acquire(self, tokens: Sequence[int],
                          owner: Optional[str] = None, *,
                          allow_cow: bool = True,
                          namespace: str = "") -> tuple[list[int], int]:
        """Longest shared prefix of ``tokens``, capped one token short
        (the first sampled token needs real last-token logits — the same
        cap the flat ``match_prefix`` applies). Returns ``(pages,
        covered_tokens)``: device pages the caller now owns one
        reference to each, in table order. Full-block hits share by
        incref (live, ref>0 — copy-on-write discipline: nobody ever
        writes claimed positions); host blocks promote in one batched
        upload; a sub-page divergence allocates a fresh private page and
        device-copies only the shared tail (``allow_cow=False`` keeps
        the match page-aligned — the handoff-adoption path needs that).
        Pool exhaustion mid-walk truncates the match rather than
        failing the admission."""
        pg = self.page_size
        cap = len(tokens) - 1
        pages: list[int] = []
        promote: list[tuple[int, bytes]] = []
        # Per-match attribution the engine reads right back (scheduler-
        # confined, like the caller): how much of this hit rode a
        # host-tier promotion or a COW tail copy.
        self.last_promoted = 0
        self.last_cow_tokens = 0
        try:
            return self._match_locked(tokens, owner, allow_cow, pg, cap,
                                      pages, promote, namespace)
        except Exception as exc:
            # Balance the books and miss: every acquired page holds
            # exactly one of our references, and a promoted node whose
            # upload may not have landed must not stay matchable.
            with self._lock:
                for pid, _ in promote:
                    node = self._by_page.get(pid)
                    if node is not None:
                        self._drop_subtree(node)
                if pages:
                    self._allocator.free(pages)
            logger.error("radix match failed; recomputing prefix: %s", exc)
            return [], 0

    def _match_locked(self, tokens, owner, allow_cow, pg, cap,
                      pages, promote, namespace="") -> tuple[list[int], int]:
        from kubeflow_tpu.serve.paged import PagePoolExhausted

        with self._lock:
            self.stats["prefix_queries"] += 1
            # Mirror into the allocator's historical counters — one
            # hit/query surface whichever index is active.
            self._allocator.stats["prefix_queries"] += 1
            now = time.monotonic()
            covered = 0
            node = self.root(namespace)
            chain: tuple = ()
            # One deadline for ALL remote-store work this match (probe +
            # fetch): armed lazily at the first remote touch so hits
            # that never leave memory pay nothing.
            remote_deadline: Optional[float] = None
            while covered + pg <= cap:
                blk = tuple(tokens[covered:covered + pg])
                child = node.children.get(blk)
                if child is None and self._remote_store is not None:
                    # Out of in-memory tree: another replica (or a dead
                    # incarnation of this one) may have published this
                    # block — the conversation-failover path.
                    if remote_deadline is None:
                        remote_deadline = (time.monotonic()
                                           + self.remote_deadline_s)
                    child = self._probe_remote_child(
                        node, blk, namespace, chain,
                        remote_deadline - time.monotonic())
                if child is None or child.tier == TIER_MIGRATING \
                        or child.tier == TIER_DEAD:
                    break
                if child.tier in (TIER_HOST, TIER_SPILLING):
                    try:
                        pid = self._allocator.alloc(1, owner=owner)[0]
                    except PagePoolExhausted:
                        break
                    if child.tier not in (TIER_HOST, TIER_SPILLING):
                        # The alloc's eviction callback can cascade a
                        # dropped subtree over ``child`` (same hazard as
                        # the COW tail): its blob is gone — miss.
                        self._allocator.free([pid])
                        break
                    # Promotion: the node returns to the device tier; the
                    # fresh ref (alloc) is the matcher's sharer ref, and
                    # ``retained`` keeps the page cached after release.
                    # A SPILLING node promotes identically — the in-
                    # flight publish kept its own blob reference and its
                    # install step discards on the tier check.
                    child.page = pid
                    child.tier = TIER_DEVICE
                    blob, child.blob = child.blob, None
                    self._host_count -= 1
                    self._by_page[pid] = child
                    self._allocator.retained.add(pid)
                    promote.append((pid, blob))
                    self.stats["pages_promoted"] += 1
                elif child.tier == TIER_REMOTE:
                    if remote_deadline is None:
                        remote_deadline = (time.monotonic()
                                           + self.remote_deadline_s)
                    blob = self._fetch_remote_blob(
                        child.blob, remote_deadline - time.monotonic())
                    if blob is None:
                        break        # timed out / corrupt → shorter match
                    try:
                        pid = self._allocator.alloc(1, owner=owner)[0]
                    except PagePoolExhausted:
                        break
                    if child.tier != TIER_REMOTE:
                        self._allocator.free([pid])
                        break
                    child.page = pid
                    child.tier = TIER_DEVICE
                    child.blob = None
                    self._remote_count -= 1
                    self._by_page[pid] = child
                    self._allocator.retained.add(pid)
                    promote.append((pid, blob))
                    self.stats["pages_promoted_remote"] += 1
                    self.stats["remote_promote_bytes"] += len(blob)
                else:
                    # Device hit (possibly still owned by a decoding
                    # request): one more sharer, stamped per owner.
                    self._allocator.incref([child.page], owner=owner)
                child.last_used = now
                pages.append(child.page)
                covered += pg
                chain = chain + (blk,)
                node = child
            # Sub-page tail: the query continues into (or diverges
            # inside) a cached block — copy only the shared part.
            rem = cap - covered
            if allow_cow and rem > 0 and self._copy_pages is not None:
                window = tuple(tokens[covered:covered + pg])
                best, best_len = None, 0
                for cand in list(node.children.values()) + node.partials:
                    if cand.tier == TIER_DEAD:
                        continue
                    n = min(_lcp(cand.block, window), rem)
                    if n > best_len:
                        best, best_len = cand, n
                if best is not None and best_len > 0:
                    cow = self._cow_tail(best, owner)
                    if cow is not None:
                        pages.append(cow)
                        covered += best_len
                        best.last_used = now
                        self.stats["tokens_cow"] += best_len
                        self.last_cow_tokens = best_len
            if covered:
                self.stats["prefix_hits"] += 1
                self._allocator.stats["prefix_hits"] += 1
                self.stats["tokens_matched"] += covered
        if promote:
            self._upload_blobs(promote)
            self.last_promoted = len(promote)
        return pages, covered

    def _cow_tail(self, src: _Node, owner) -> Optional[int]:
        """Fresh private page holding ``src``'s claimed content: device
        copy for a device source, blob upload for a host one. Returns
        the page id, or None when the pool is dry / the source is
        mid-migration."""
        from kubeflow_tpu.serve.paged import PagePoolExhausted

        if src.tier not in (TIER_DEVICE, TIER_HOST, TIER_SPILLING):
            return None
        try:
            fresh = self._allocator.alloc(1, owner=owner)[0]
        except PagePoolExhausted:
            return None
        if src.tier not in (TIER_DEVICE, TIER_HOST, TIER_SPILLING):
            # The alloc above reclaims ref-0 indexed pages through the
            # eviction callback — and under pool pressure the coldest
            # cached page is often ``src`` itself, which arrives here
            # DEAD with page and blob cleared. Nothing left to copy.
            self._allocator.free([fresh])
            return None
        try:
            if src.tier == TIER_DEVICE:
                self._copy_pages([src.page], [fresh])
            else:
                self._upload_blobs([(fresh, src.blob)])
            self.stats["cow_copies"] += 1
            return fresh
        except Exception:
            # The fresh ref must not strand on a failed device call.
            self._allocator.free([fresh])
            raise

    def _upload_blobs(self, items: list) -> None:
        """ONE batched host→device upload for ``items`` of
        ``(page_id, wire_blob)``. Blobs decode zero-copy; the engine's
        upload closure packs them into its padded buffer directly (one
        host copy total on the admission path). int8 blobs (wire v2)
        carry their scale rows, which ride the same batched upload."""
        ids = [pid for pid, _ in items]
        with self._lock:
            self.stats["promote_wire_bytes"] += sum(
                len(blob) for _, blob in items)
        ks, vs, sks, svs = [], [], [], []
        for _, blob in items:
            k, v, sk, sv = pages_from_wire(blob)
            ks.append(k)
            vs.append(v)
            sks.append(sk)
            svs.append(sv)
        if any(s is not None for s in sks):
            if any(s is None for s in sks):
                raise ValueError(
                    "mixed quantized/full-dtype blobs in one promote batch")
            self._upload_pages(ids, ks, vs, sks, svs)
        else:
            self._upload_pages(ids, ks, vs)

    # -- remote third tier (fleet-wide KV fabric) ----------------------------

    def _remote_key(self, namespace: str, chain: tuple) -> str:
        """Registry name for one radix block chain. Deterministic across
        replicas: same fabric signature + namespace + token blocks →
        same name, which is what makes a dead engine's KV discoverable
        by a survivor that never saw the original request."""
        h = hashlib.sha256(
            repr((self.fabric_sig, namespace, chain)).encode("utf-8"))
        return "kv-" + h.hexdigest()[:40]

    def _chain_of(self, node: _Node) -> Optional[tuple]:
        # requires_lock: _lock
        """Root-to-node block chain, or None if any hop is a partial
        leaf (sub-page blocks are not remotely addressable — their
        content is position-dependent within an unclaimed page)."""
        chain: list = []
        n = node
        while n is not None and n.parent is not None:
            if len(n.block) != self.page_size:
                return None
            chain.append(n.block)
            n = n.parent
        return tuple(reversed(chain))

    def _namespace_of(self, node: _Node) -> str:
        # requires_lock: _lock
        n = node
        while n.parent is not None:
            n = n.parent
        for ns, r in self._roots.items():
            if r is n:
                return ns
        return ""

    def _remote_call(self, fn: Callable, timeout_s: float):
        """One store operation under a hard deadline. The store API has
        no timeout of its own, so a wedged store (the seeded chaos
        fault) is bounded by a sacrificial daemon thread: on timeout
        the caller degrades to recompute and the thread dies with its
        blocking call whenever the store unwedges. Returns
        ``(ok, value_or_exception)``."""
        if timeout_s <= 0:
            return False, TimeoutError("remote KV deadline exhausted")
        box: dict = {}

        def run():
            try:
                box["v"] = fn()
            # Not swallowed: relayed through the box and re-surfaced
            # to the caller as (False, exc).
            # lint: disable=C303
            except BaseException as exc:
                box["e"] = exc

        t = threading.Thread(target=run, daemon=True, name="kv-remote-io")
        t.start()
        t.join(timeout_s)
        if t.is_alive():
            return False, TimeoutError("remote KV store deadline")
        if "e" in box:
            return False, box["e"]
        return True, box.get("v")

    def _probe_remote_child(self, parent: _Node, blk: tuple,
                            namespace: str, chain: tuple,
                            budget_s: float) -> Optional[_Node]:
        # requires_lock: _lock (held across the bounded store probe —
        # the sacrificial thread never takes _lock, so no deadlock, and
        # budget_s caps how long admission can stall on it)
        key = self._remote_key(namespace, chain + (blk,))
        ok, val = self._remote_call(
            lambda: self._remote_store.lookup(key), budget_s)
        if not ok:
            if isinstance(val, TimeoutError):
                self.stats["remote_promote_timeouts"] += 1
            # FileNotFoundError = nobody published this chain: the
            # ordinary cold-prompt miss, not a failure.
            return None
        child = _Node(blk, None, parent)
        child.tier = TIER_REMOTE
        child.blob = val               # the cas:// uri
        parent.children[blk] = child
        self._remote_count += 1
        self.stats["nodes"] += 1
        self.stats["remote_registry_hits"] += 1
        return child

    def _fetch_remote_blob(self, uri: str,
                           budget_s: float) -> Optional[bytes]:
        # requires_lock: _lock (bounded, same contract as the probe)
        ok, val = self._remote_call(
            lambda: self._remote_store.get_bytes(uri), budget_s)
        if not ok:
            if isinstance(val, TimeoutError):
                self.stats["remote_promote_timeouts"] += 1
            else:
                self.stats["remote_promote_errors"] += 1
            return None
        blob = val
        if uri.startswith(_CAS) and hashlib.sha256(blob).hexdigest() \
                != uri[len(_CAS):]:
            # Truncated/corrupt tier blob (the seeded fault): the
            # content address IS the manifest checksum — reject before
            # any page is allocated, degrade to recompute.
            self.stats["remote_blobs_corrupt"] += 1
            return None
        return blob

    def _remote_publish(self, blob: bytes, key: str) -> str:
        """Publish one wire blob: CAS put, then registry bind. Runs on
        the migration thread (or the synchronous drain) — never the
        scheduler. Crash between put and register leaves an
        unregistered blob for the GC sweep, never a dangling name."""
        def op(_attempt):
            uri = self._remote_store.put_bytes(blob)
            try:
                self._remote_store.register(key, "0", uri)
            except ValueError:
                # A racing replica bound this chain to its own
                # (equivalent-content) blob first. Keep OUR uri locally
                # — the bytes exist either way; the registry simply
                # points survivors at the first writer's copy.
                pass
            return uri
        return call_with_retry(op, policy=STORE_POLICY,
                               retry_on=(OSError,))

    # -- registration --------------------------------------------------------

    def insert(self, tokens: Sequence[int], pages: Sequence[int],
               n_tokens: Optional[int] = None, *,
               namespace: str = "") -> None:
        """Index ``tokens[:n_tokens]``'s KV: full blocks become (or
        confirm) tree nodes pointing at the registering slot's pages, a
        sub-page remainder becomes (or extends) a partial leaf. Existing
        nodes keep their page (first writer wins — the duplicate page
        stays slot-owned and frees at release, exactly like the flat
        cache). Pages referenced here may still be LIVE (the owner keeps
        decoding past the claimed content) — that is the live-sharing
        contract, not a hazard."""
        pg = self.page_size
        n_tokens = len(tokens) if n_tokens is None else min(n_tokens,
                                                            len(tokens))
        with self._lock:
            now = time.monotonic()
            node = self.root(namespace)
            nfull = n_tokens // pg
            for i in range(min(nfull, len(pages))):
                blk = tuple(tokens[i * pg:(i + 1) * pg])
                child = node.children.get(blk)
                if child is None:
                    page = pages[i]
                    if page in self._by_page:
                        break      # already indexed on another path
                    child = _Node(blk, page, node)
                    node.children[blk] = child
                    self._by_page[page] = child
                    self._allocator.retained.add(page)
                    self.stats["nodes"] += 1
                    # A full block subsumes any partial leaf it extends.
                    for pn in list(node.partials):
                        if blk[:len(pn.block)] == pn.block:
                            self._drop_subtree(pn)
                elif child.tier == TIER_DEAD:
                    break
                child.last_used = now
                node = child
            tail = tuple(tokens[nfull * pg:n_tokens])
            if tail and nfull < len(pages):
                self._insert_partial(node, tail, pages[nfull], now)

    def _insert_partial(self, parent: _Node, tail: tuple, page: int,
                        now: float) -> None:
        if any(blk[:len(tail)] == tail for blk in parent.children):
            return                       # a full block already covers it
        for pn in parent.partials:
            if pn.page == page:
                # Same page re-registered with more content (a finished
                # request upgrading its prompt tail with generated
                # tokens): extend the claim in place.
                if len(tail) > len(pn.block) \
                        and tail[:len(pn.block)] == pn.block:
                    pn.block = tail
                pn.last_used = now
                return
            if len(tail) <= len(pn.block) \
                    and pn.block[:len(tail)] == tail:
                pn.last_used = now
                return                   # existing partial covers more
        if page in self._by_page:
            return
        # Longer content on a different page replaces the covered leaf.
        for pn in list(parent.partials):
            if len(pn.block) < len(tail) \
                    and tail[:len(pn.block)] == pn.block:
                self._drop_subtree(pn)
        if len(parent.partials) >= MAX_PARTIALS:
            self._drop_subtree(min(parent.partials,
                                   key=lambda n: n.last_used))
        leaf = _Node(tail, page, parent)
        parent.partials.append(leaf)
        self._by_page[page] = leaf
        self._allocator.retained.add(page)
        self.stats["nodes"] += 1

    # -- eviction (allocator callback + host capacity) -----------------------

    def _on_evict(self, page: int) -> None:
        """The allocator reclaimed a ref-0 indexed page for a fresh
        alloc: drop the node; its subtree is unreachable now and
        cascades back to the pool/host-free state."""
        with self._lock:
            node = self._by_page.pop(page, None)
            if node is None:
                return
            self.stats["evictions"] += 1
            node.page = None             # the allocator owns it again
            self._drop_subtree(node)

    def _drop_subtree(self, node: _Node) -> None:
        # requires_lock: _lock
        parent = node.parent
        if parent is not None:
            parent.children.pop(node.block, None)
            if node in parent.partials:
                parent.partials.remove(node)
        stack, drop_pages = [node], []
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            stack.extend(n.partials)
            if n.tier == TIER_DEVICE and n.page is not None:
                self._by_page.pop(n.page, None)
                if self._allocator.ref(n.page) == 0:
                    drop_pages.append(n.page)
                else:
                    # Still shared by a live request: the sharer keeps
                    # its reference; the page just stops being indexed.
                    self._allocator.retained.discard(n.page)
            elif n.tier in (TIER_HOST, TIER_SPILLING):
                n.blob = None
                self._host_count -= 1
            elif n.tier == TIER_REMOTE:
                # The store blob stays — it is a fleet asset other
                # replicas may still promote from; unreferenced blobs
                # are the GC sweep's job, not the tree's.
                n.blob = None
                self._remote_count -= 1
            n.tier = TIER_DEAD       # a mid-migration install discards
            n.page = None
            n.children = {}
            n.partials = []
            self.stats["nodes"] -= 1
        if drop_pages:
            self._allocator.drop_cached(drop_pages)

    def _evict_host_lru(self, n: int) -> None:
        # requires_lock: _lock
        while n > 0:
            hosts = [node for node in self._iter_nodes()
                     if node.tier == TIER_HOST]
            if not hosts:
                return
            victim = min(hosts, key=lambda nd: nd.last_used)
            before = self._host_count
            self._drop_subtree(victim)
            self.stats["host_evictions"] += 1
            n -= max(before - self._host_count, 1)

    def _iter_nodes(self):
        # requires_lock: _lock
        stack = [n for r in self._roots.values()
                 for n in list(r.children.values()) + r.partials]
        while stack:
            n = stack.pop()
            yield n
            stack.extend(n.children.values())
            stack.extend(n.partials)

    # -- demotion (scheduler side) + migration thread ------------------------

    def tick(self, now: Optional[float] = None, *,
             busy: bool = False, force: bool = False) -> int:
        """Periodic demotion scan (called from the engine's scheduler
        step): pick cold sharer-free device pages LRU, enqueue ONE
        batched device-side gather, free the device pages (program
        order makes that safe — the gather reads pre-free values), and
        hand the fetch to the migration thread. Returns pages demoted
        this pass.

        ``busy`` = the scheduler has foreground work this step.
        Migration then YIELDS unless the pool is actually under
        pressure (free+cached running low): think-time gaps and
        inter-session idle provide ample demotion windows, and an
        admission must never queue behind cold-page bookkeeping — but
        when the pool is nearly exhausted, demoting now is what saves
        the cached content from lossy LRU eviction, so it runs anyway."""
        if self.host_pages <= 0 or self._fetch_pages is None:
            return 0
        now = time.monotonic() if now is None else now
        if not force and now - self._last_scan < self._scan_interval:
            return 0
        self._spill_scan(now, force=force)
        # Pressure demotion: when memory is about to be reclaimed
        # destructively (LRU eviction would DESTROY cached content),
        # demote to host first, age threshold be damned. The pressure
        # signal is pluggable (pressure_fn >= 1.0 = urgent): the engine
        # folds pool occupancy with its queue-delay-vs-budget ratio and
        # adapter hot-load backpressure, so demotion urgency tracks the
        # same signals the SLO autoscaler acts on instead of only the
        # free-list level. Otherwise only genuinely cold pages move,
        # and never while foreground work would queue behind the
        # bookkeeping.
        urgent = self.pressure() >= 1.0
        if busy and not urgent and not force:
            return 0
        self._last_scan = now
        with self._lock:
            cands: list[_Node] = []
            # Urgent mode still protects HOT pages (used within two
            # scan windows): demoting a shared prefix the very next
            # arrival will match would buy one free page at the cost of
            # a promotion round-trip under an already-dry pool — the
            # churn spiral, not a rescue.
            floor = (0.0 if force
                     else 2 * self._scan_interval if urgent
                     else self.demote_after_s)
            for p in self._allocator.reclaimable_lru():
                node = self._by_page.get(p)
                if node is None or node.tier != TIER_DEVICE:
                    continue
                if now - node.last_used < floor:
                    continue
                cands.append(node)
                if len(cands) >= self.migrate_batch_pages:
                    break
            if not cands:
                return 0
            room = self.host_pages - self._host_count - self._migrating
            if len(cands) > room:
                if force:
                    # Drain mode: NEVER destroy host content to make
                    # room — the next pass's spill frees it losslessly.
                    cands = cands[:max(room, 0)]
                else:
                    self._evict_host_lru(len(cands) - room)
                    room = (self.host_pages - self._host_count
                            - self._migrating)
                    cands = cands[:max(room, 0)]
            if not cands:
                return 0
            ids = [n.page for n in cands]
            fetched = self._fetch_pages(ids)
            # Quantized pools fetch 4 planes (k, v, scale_k, scale_v);
            # full-dtype pools fetch 2.
            if len(fetched) == 4:
                k_dev, v_dev, ks_dev, vs_dev = fetched
            else:
                (k_dev, v_dev), ks_dev, vs_dev = fetched, None, None
            for n in cands:
                self._by_page.pop(n.page, None)
                n.page = None
                n.tier = TIER_MIGRATING
                self._migrating += 1
            self._allocator.drop_cached(ids)
            self.stats["demote_batches"] += 1
        self._queue.put(("demote", cands, k_dev, v_dev, ks_dev, vs_dev))
        return len(ids)

    def _spill_scan(self, now: float, *, force: bool = False) -> None:
        """Aging spill host→store: full-block host blobs idle past
        ``remote_after_s`` hand off to the migration thread for publish.
        Spill is PROACTIVE (fires with host room to spare) — the point
        is failover durability, not just capacity: a conversation's KV
        must already be in the store when its engine dies."""
        if self._remote_store is None:
            return
        spills: list = []
        with self._lock:
            for node in self._iter_nodes():
                if node.tier != TIER_HOST:
                    continue
                if not force and now - node.last_used < self.remote_after_s:
                    continue
                chain = self._chain_of(node)
                if chain is None:
                    continue       # partial leaves stay host-tier
                ns = self._namespace_of(node)
                node.tier = TIER_SPILLING
                self._spilling += 1
                # The blob rides the queue item by value: a promote or
                # eviction racing the publish clears ``node.blob``
                # without invalidating the in-flight bytes.
                spills.append((node, node.blob,
                               self._remote_key(ns, chain)))
                if len(spills) >= self.migrate_batch_pages:
                    break
        if spills:
            self._queue.put(("spill", spills))

    def _migrate_loop(self) -> None:
        import jax

        from kubeflow_tpu.obs.trace import get_tracer

        while not self._stop.is_set():
            try:
                # Bounded get (T801): close() pushes a None sentinel, but
                # the timeout guarantees the stop flag is rechecked even
                # if the sentinel is lost to a racing drain.
                item = self._queue.get(timeout=1.0)
            except queue.Empty:
                continue
            if item is None:
                return
            if item[0] == "spill":
                self._run_spill(item[1], get_tracer())
                continue
            _, nodes, k_dev, v_dev, ks_dev, vs_dev = item
            span = get_tracer().start_span(
                "engine.kv_migrate", direction="demote", pages=len(nodes))
            try:
                fetched = jax.device_get((k_dev, v_dev, ks_dev, vs_dev))  # sync-point: the migration thread owns this blocking fetch, never the scheduler
                k = np.asarray(fetched[0])
                v = np.asarray(fetched[1])
                ks = None if fetched[2] is None else np.asarray(fetched[2])
                vs = None if fetched[3] is None else np.asarray(fetched[3])
                with self._lock:
                    for j, n in enumerate(nodes):
                        self._migrating -= 1
                        if n.tier != TIER_MIGRATING:
                            # Evicted while the bytes were in flight:
                            # the content is unreachable — discard.
                            self.stats["demote_dropped"] += 1
                            continue
                        # Full-dtype pools call with the v1 positional
                        # signature so (k, v)-shaped monkeypatch
                        # wrappers (the seeded-wedge harnesses) survive.
                        if ks is None:
                            n.blob = pages_to_wire(k[:, j], v[:, j])
                        else:
                            n.blob = pages_to_wire(
                                k[:, j], v[:, j],
                                kv_sk=ks[:, j], kv_sv=vs[:, j])
                        n.tier = TIER_HOST
                        self._host_count += 1
                        self.stats["pages_demoted"] += 1
                        self.stats["demote_wire_bytes"] += len(n.blob)
                span.end("ok")
            except Exception as exc:
                # A failed migration batch loses cached content (it was
                # already freed device-side) but never correctness — the
                # nodes stay MIGRATING/DEAD and simply miss on match.
                logger.error("kv migration batch failed: %s", exc)
                span.end("error")

    def _run_spill(self, spills: list, tracer) -> None:
        """Migration-thread half of the host→store spill: publish each
        blob (CAS put + registry bind, retried under STORE_POLICY), then
        install TIER_REMOTE under the lock — or discard if a promote or
        eviction won the race. Publish failures put the node BACK on the
        host tier with a refreshed clock, so a dead store degrades to
        'third tier off' instead of a retry hot-loop."""
        span = tracer.start_span(
            "engine.kv_migrate", direction="spill", pages=len(spills))
        errors = 0
        for node, blob, key in spills:
            try:
                uri = self._remote_publish(blob, key)
            except Exception as exc:
                errors += 1
                with self._lock:
                    self._spilling -= 1
                    if node.tier == TIER_SPILLING:
                        node.tier = TIER_HOST
                        node.last_used = time.monotonic()
                    self.stats["remote_spill_errors"] += 1
                logger.error("kv remote spill failed: %s", exc)
                continue
            with self._lock:
                self._spilling -= 1
                if node.tier != TIER_SPILLING:
                    # Promoted or evicted while the publish was in
                    # flight: the registered blob stays valid fleet
                    # content; only this node's transition is void.
                    self.stats["remote_spill_dropped"] += 1
                    continue
                node.blob = uri
                node.tier = TIER_REMOTE
                self._host_count -= 1
                self._remote_count += 1
                self.stats["pages_demoted_remote"] += 1
                self.stats["remote_demote_bytes"] += len(blob)
        span.end("error" if errors else "ok")

    def spill_all_to_remote(self, timeout_s: float = 10.0) -> int:
        """Scale-down drain hook: push EVERY publishable cached page out
        to the store — forced demote passes (device→host, age floor 0)
        interleaved with forced spills (host→store) until nothing moves
        — so a replica leaving the fleet strands no conversation.
        Scheduler-confined (call with the engine idle/draining).
        Returns pages published."""
        if self._remote_store is None:
            return 0
        before = self.snapshot()["pages_demoted_remote"]
        done = before
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self._fetch_pages is not None and self.host_pages > 0:
                moved = self.tick(force=True)
            else:
                # Host tier only (no demote machinery wired): still
                # publish what it holds.
                moved = 0
                self._spill_scan(time.monotonic(), force=True)
            # The forced tick also force-spilled the host tier
            # (_spill_scan(force=True)); wait both halves out.
            try:
                self.drain_migrations(
                    max(deadline - time.monotonic(), 0.01))
            except TimeoutError:
                break
            now_done = self.snapshot()["pages_demoted_remote"]
            if not moved and now_done == done:
                break        # only unpublishable content (partials) left
            done = now_done
        return done - before

    def drain_migrations(self, timeout_s: float = 5.0) -> None:
        """Test/audit hook: wait until no demotion batch or remote
        spill is in flight."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if self._migrating == 0 and self._spilling == 0:
                    return
            time.sleep(0.005)
        raise TimeoutError("kv migration batches still in flight")

    def close(self) -> None:
        from kubeflow_tpu.runtime.sanitize import assert_threads_quiescent

        self._stop.set()
        if self._thread is not None:
            self._queue.put(None)
            self._thread.join(timeout=5.0)
            self._thread = None
        if getattr(self._allocator, "on_evict", None) is self._on_evict:
            self._allocator.on_evict = None
        # KFTPU_SANITIZE=threads: the kv-migrate thread binds to this
        # tier — a survivor raises with its creation site. No-op when
        # the mode is off.
        assert_threads_quiescent(owner=self, grace_s=5.0)
