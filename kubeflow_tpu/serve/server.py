"""Model server: HTTP protocol surface over the LLM engine.

Implements the three protocol families of the reference's model server in one
stdlib-only server (no fastapi in this image):

- v1 protocol  ((U) kserve kserve/protocol/rest/v1_endpoints.py):
  POST /v1/models/{name}:predict   {"instances": [...]}
- v2 open-inference protocol ((U) kserve v2_endpoints.py):
  GET  /v2/models/{name}           metadata
  POST /v2/models/{name}/infer     {"inputs": [{name,shape,datatype,data}]}
- OpenAI-compatible LLM surface ((U) kserve python/huggingfaceserver):
  POST /v1/completions, /v1/chat/completions (stream=true → SSE)

Plus /healthz (readiness) and /metrics (Prometheus text format).
Threaded stdlib server: handlers block on the engine's request stream; the
engine thread does the batching, so concurrency costs one OS thread per
in-flight request — fine at platform scale, and zero dependencies.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

from kubeflow_tpu.serve.engine import LLMEngine, Request, SamplingParams
from kubeflow_tpu.serve.tokenizer import Tokenizer, get_tokenizer


class ModelServer:
    def __init__(self, name: str, engine: LLMEngine, *,
                 tokenizer: Optional[Tokenizer] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.name = name
        self.engine = engine
        self.tokenizer = tokenizer or get_tokenizer("byte")
        self._in_flight = 0
        self._in_flight_lock = threading.Lock()
        handler = _make_handler(self)
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> None:
        self.engine.start()
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True, name="model-server")
        self._thread.start()

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self.engine.stop()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    # -- request plumbing ------------------------------------------------------

    def track(self, delta: int) -> None:
        with self._in_flight_lock:
            self._in_flight += delta

    @property
    def in_flight(self) -> int:
        with self._in_flight_lock:
            return self._in_flight

    def sampling_from(self, body: dict[str, Any]) -> SamplingParams:
        return SamplingParams(
            max_new_tokens=int(body.get("max_tokens", 64)),
            temperature=float(body.get("temperature", 0.0)),
            top_k=int(body.get("top_k", 0)),
            stop_token=self.tokenizer.eos_id,
        )

    def metrics_text(self) -> str:
        snap = self.engine.metrics.snapshot()
        lines = [
            "# TYPE kftpu_serving_requests_total counter",
            f"kftpu_serving_requests_total {snap['requests_completed']}",
            "# TYPE kftpu_serving_tokens_total counter",
            f"kftpu_serving_tokens_total {snap['tokens_generated']}",
            "# TYPE kftpu_serving_in_flight gauge",
            f"kftpu_serving_in_flight {self.in_flight}",
        ]
        for k in ("ttft_p50_ms", "ttft_p99_ms", "tpot_p50_ms",
                  "requests_per_sec", "tokens_per_sec"):
            if k in snap:
                lines.append(f"kftpu_serving_{k} {snap[k]}")
        return "\n".join(lines) + "\n"


def _make_handler(server: ModelServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *args) -> None:  # quiet
            pass

        # -- helpers ----------------------------------------------------------

        def _json(self, code: int, obj: Any) -> None:
            data = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _text(self, code: int, text: str, ctype="text/plain") -> None:
            data = text.encode()
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _body(self) -> dict:
            n = int(self.headers.get("Content-Length", 0))
            return json.loads(self.rfile.read(n) or b"{}")

        # -- GET ---------------------------------------------------------------

        def do_GET(self) -> None:
            if self.path in ("/healthz", "/v2/health/ready", "/v2/health/live"):
                self._json(200, {"status": "ok", "name": server.name})
            elif self.path == "/metrics":
                self._text(200, server.metrics_text())
            elif self.path == "/v1/models":
                self._json(200, {"models": [server.name]})
            elif self.path == f"/v2/models/{server.name}":
                cfg = server.engine.cfg
                self._json(200, {
                    "name": server.name,
                    "platform": "kubeflow-tpu-llm",
                    "inputs": [{"name": "text", "datatype": "BYTES",
                                "shape": [-1]}],
                    "outputs": [{"name": "text", "datatype": "BYTES",
                                 "shape": [-1]}],
                    "config": {"vocab_size": cfg.vocab_size,
                               "max_seq_len": cfg.max_seq_len},
                })
            else:
                self._json(404, {"error": f"not found: {self.path}"})

        # -- POST --------------------------------------------------------------

        def do_POST(self) -> None:
            server.track(1)
            try:
                body = self._body()
                if self.path == f"/v1/models/{server.name}:predict":
                    self._v1_predict(body)
                elif self.path == f"/v2/models/{server.name}/infer":
                    self._v2_infer(body)
                elif self.path == "/v1/completions":
                    self._completions(body, chat=False)
                elif self.path == "/v1/chat/completions":
                    self._completions(body, chat=True)
                else:
                    self._json(404, {"error": f"not found: {self.path}"})
            except ValueError as exc:
                self._json(400, {"error": str(exc)})
            except Exception as exc:   # surface, don't hide
                self._json(500, {"error": f"{type(exc).__name__}: {exc}"})
            finally:
                server.track(-1)

        def _generate_text(self, prompt: str, body: dict) -> tuple[str, Request]:
            toks = server.tokenizer.encode(prompt)
            req = server.engine.submit(toks, server.sampling_from(body))
            out = req.result(timeout=float(body.get("timeout", 300)))
            text = server.tokenizer.decode(
                [t for t in out if t != server.tokenizer.eos_id])
            return text, req

        def _v1_predict(self, body: dict) -> None:
            instances = body.get("instances")
            if not isinstance(instances, list):
                raise ValueError("body must contain 'instances': [...]")
            preds = [self._generate_text(str(inst), body)[0]
                     for inst in instances]
            self._json(200, {"predictions": preds})

        def _v2_infer(self, body: dict) -> None:
            inputs = body.get("inputs")
            if not isinstance(inputs, list) or not inputs:
                raise ValueError("body must contain 'inputs': [...]")
            texts = []
            for inp in inputs:
                for datum in inp.get("data", []):
                    texts.append(self._generate_text(str(datum), body)[0])
            self._json(200, {
                "model_name": server.name,
                "outputs": [{"name": "text", "datatype": "BYTES",
                             "shape": [len(texts)], "data": texts}],
            })

        def _completions(self, body: dict, *, chat: bool) -> None:
            if chat:
                msgs = body.get("messages", [])
                prompt = "\n".join(f"{m.get('role', 'user')}: {m.get('content', '')}"
                                   for m in msgs) + "\nassistant:"
            else:
                prompt = body.get("prompt", "")
                if isinstance(prompt, list):
                    prompt = prompt[0] if prompt else ""
            if body.get("stream"):
                return self._completions_stream(prompt, body, chat=chat)
            text, req = self._generate_text(prompt, body)
            usage = {"prompt_tokens": len(req.prompt_tokens),
                     "completion_tokens": len(req.output_tokens),
                     "total_tokens": len(req.prompt_tokens) + len(req.output_tokens)}
            if chat:
                choice = {"index": 0, "finish_reason": req.finish_reason,
                          "message": {"role": "assistant", "content": text}}
                obj = "chat.completion"
            else:
                choice = {"index": 0, "finish_reason": req.finish_reason,
                          "text": text}
                obj = "text_completion"
            self._json(200, {
                "id": req.id, "object": obj, "created": int(time.time()),
                "model": server.name, "choices": [choice], "usage": usage,
            })

        def _completions_stream(self, prompt: str, body: dict, *, chat: bool) -> None:
            toks = server.tokenizer.encode(prompt)
            req = server.engine.submit(toks, server.sampling_from(body))
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

            def chunk(data: str) -> None:
                payload = f"data: {data}\n\n".encode()
                self.wfile.write(f"{len(payload):x}\r\n".encode()
                                 + payload + b"\r\n")
                self.wfile.flush()

            while True:
                tok = req.stream.get(timeout=float(body.get("timeout", 300)))
                if tok is None:
                    break
                if tok == server.tokenizer.eos_id:
                    continue
                piece = server.tokenizer.decode([tok])
                if chat:
                    delta = {"choices": [{"index": 0,
                                          "delta": {"content": piece}}]}
                else:
                    delta = {"choices": [{"index": 0, "text": piece}]}
                chunk(json.dumps({"id": req.id, "object": "chunk",
                                  "model": server.name, **delta}))
            chunk("[DONE]")
            self.wfile.write(b"0\r\n\r\n")

    return Handler
