"""Model server: HTTP protocol surface over one LLM engine or a multi-model
repository.

Implements the three protocol families of the reference's model server in one
stdlib-only server (no fastapi in this image):

- v1 protocol  ((U) kserve kserve/protocol/rest/v1_endpoints.py):
  POST /v1/models/{name}:predict   {"instances": [...]}
  POST /v1/models/{name}:explain   {"instances": [...]} → per-token
       attribution from the configured explainer hop (serve/explain.py)
- v2 open-inference protocol ((U) kserve v2_endpoints.py):
  GET  /v2/models/{name}           metadata
  POST /v2/models/{name}/infer     {"inputs": [{name,shape,datatype,data}]}
- OpenAI-compatible LLM surface ((U) kserve python/huggingfaceserver):
  POST /v1/completions, /v1/chat/completions (stream=true → SSE; the
  "model" body field routes in multi-model mode)

Multi-model mode (≈ model agent + ModelMesh — SURVEY.md §2.3#29): construct
with a ``ModelRepository`` and the server adds the v2 repository API
(``GET /v2/repository/index``, ``POST /v2/repository/models/{m}/load|
unload``) and per-request routing with LRU load-on-demand.

Plus /healthz (readiness) and /metrics (Prometheus text format).
Threaded stdlib server: handlers block on the engine's request stream; the
engine thread does the batching, so concurrency costs one OS thread per
in-flight request — fine at platform scale, and zero dependencies.
"""

from __future__ import annotations

import http.client
import json
import queue
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional
from urllib.parse import urlparse

from kubeflow_tpu.core.headers import (
    DEADLINE_HEADER, DECODE_ALTS_HEADER, DECODE_BACKEND_HEADER,
    HANDOFF_DTYPE_HEADER, HANDOFF_WIRE_HEADER, MODEL_HEADER, QOS_HEADER,
    TRACE_HEADER,
)
from kubeflow_tpu.obs.fleet import spans_export_payload
from kubeflow_tpu.obs.registry import MetricsRegistry, contract_note_header
from kubeflow_tpu.obs.trace import debug_traces_payload, get_tracer
from kubeflow_tpu.core.serving import QOS_DEFAULT
from kubeflow_tpu.serve.engine import (
    EngineOverloaded, HOST_GAP_BUCKETS, LLMEngine, QUEUE_DELAY_BUCKETS,
    Request, SamplingParams,
)
from kubeflow_tpu.serve.retry import (
    call_with_retry, env_float, handoff_policy,
)
from kubeflow_tpu.serve.router import quiet_handle_error
from kubeflow_tpu.serve.tokenizer import Tokenizer, get_tokenizer

#: Handoff wire versions this server can adopt (serve/handoff.py): v1 =
#: raw K/V planes, v2 = + int8 scale rows. A payload tagged with
#: anything else 409s at submit — the mixed-version-fleet guard.
SUPPORTED_HANDOFF_WIRE = ("1", "2")


def _raise_for_reaped(req: Request) -> None:
    """Map an engine-side terminal failure to the exception the protocol
    layer translates into an explicit HTTP status (504/429/500). A request
    the scheduler reaped returns normally from ``result()`` — with a
    failure ``finish_reason`` and possibly zero output tokens — and MUST
    NOT be served as a successful (empty) completion."""
    if req.finish_reason in ("deadline", "cancelled"):
        raise TimeoutError(
            f"request {req.id} {req.finish_reason} before completion")
    if req.finish_reason == "shed":
        raise EngineOverloaded(
            f"request {req.id} shed: queue delay exceeded budget")
    if req.finish_reason == "error":
        raise RuntimeError(f"request {req.id} failed in-engine")

def open_handoff(decode_url: str, payload, *, chat: bool, qos: str,
                 trace_hdr: Optional[str], deadline_s: Optional[float],
                 timeout: float):
    """POST a KV handoff to a decode replica; returns ``(conn, resp)``
    once the decode side ACKED (HTTP 200 — the payload bytes are in its
    memory, so the prefill side may release its page hold). Raises
    OSError on anything short of an ack, which is the caller's signal to
    ``fail_handoff`` and recompute locally.

    Cross-host hardening (ISSUE 17): connect+send and ack-wait carry
    SEPARATE budgets ($KFTPU_HANDOFF_CONNECT_S / $KFTPU_HANDOFF_ACK_S —
    a dead host fails the connect in seconds; a live-but-wedged decode
    replica fails the ack wait without holding the prefill's pages for
    the whole request deadline), and the POST declares its cache dtype
    and wire version so a mixed-version fleet REJECTS at submit (409 →
    OSError here → retry elsewhere / recompute) instead of corrupting
    pages."""
    connect_s = min(env_float("KFTPU_HANDOFF_CONNECT_S", 5.0), timeout)
    ack_s = min(env_float("KFTPU_HANDOFF_ACK_S", 30.0), timeout)
    parsed = urlparse(decode_url)
    conn = http.client.HTTPConnection(parsed.hostname or "127.0.0.1",
                                      parsed.port or 80, timeout=connect_s)
    headers = {"Content-Type": "application/octet-stream",
               QOS_HEADER: qos,
               HANDOFF_DTYPE_HEADER: payload.cache_dtype or "full",
               HANDOFF_WIRE_HEADER:
                   "2" if payload.cache_dtype else "1"}
    contract_note_header(QOS_HEADER, direction="set")
    contract_note_header(HANDOFF_DTYPE_HEADER, direction="set")
    contract_note_header(HANDOFF_WIRE_HEADER, direction="set")
    if trace_hdr:
        headers[TRACE_HEADER] = trace_hdr
        contract_note_header(TRACE_HEADER, direction="set")
    if deadline_s is not None:
        headers[DEADLINE_HEADER] = str(int(max(deadline_s, 0.0) * 1e3))
        contract_note_header(DEADLINE_HEADER, direction="set")
    path = "/v1/handoff" + ("?chat=1" if chat else "")
    try:
        conn.request("POST", path, body=payload.to_wire(), headers=headers)
        if conn.sock is not None:
            conn.sock.settimeout(ack_s)     # ack-hold budget
        resp = conn.getresponse()
    except (OSError, http.client.HTTPException) as exc:
        conn.close()
        raise OSError(f"handoff POST to {decode_url} failed: {exc}") from exc
    if resp.status != 200:
        body = resp.read()
        conn.close()
        raise OSError(
            f"handoff to {decode_url} rejected: HTTP {resp.status} "
            f"{body[:200]!r}")
    if conn.sock is not None:
        # Acked: the token relay may legitimately idle between decode
        # chunks — fall back to the request-wide budget.
        conn.sock.settimeout(timeout)
    return conn, resp


def open_handoff_with_retry(engine, candidates: list, payload, *,
                            chat: bool, qos: str, trace_fn,
                            deadline_s: Optional[float], timeout: float):
    """Bounded cross-replica handoff retry: attempt ``candidates`` in
    order under the shared jittered-backoff policy (serve/retry.py),
    each attempt a DIFFERENT decode replica — never hammer the one that
    just failed. Returns ``(url, conn, resp)`` on the first ack; raises
    the last OSError once every candidate (or the attempt budget) is
    exhausted — the caller's signal to take the terminal fallback
    (fail_handoff + local recompute, never a dropped request)."""
    from dataclasses import replace

    policy = handoff_policy()
    policy = replace(policy, attempts=max(
        1, min(policy.attempts, len(candidates))))

    def attempt(i: int):
        url = candidates[i]
        conn, resp = open_handoff(url, payload, chat=chat, qos=qos,
                                  trace_hdr=trace_fn(), deadline_s=deadline_s,
                                  timeout=timeout)
        return url, conn, resp

    def on_retry(_attempt: int, _exc) -> None:
        engine.metrics.note_handoff("retried")

    return call_with_retry(attempt, policy=policy, on_retry=on_retry)


def iter_sse_data(resp):
    """Yield the value of every ``data:`` line of an SSE response (the
    decode replica's token chunks), ending at stream end."""
    while True:
        line = resp.readline()
        if not line:
            return
        line = line.strip()
        if not line.startswith(b"data:"):
            continue
        yield line[5:].strip().decode()


_V1_PREDICT = re.compile(r"^/v1/models/([^/:]+):predict$")
_V1_EXPLAIN = re.compile(r"^/v1/models/([^/:]+):explain$")
_V2_MODEL = re.compile(r"^/v2/models/([^/]+)$")
_V2_INFER = re.compile(r"^/v2/models/([^/]+)/infer$")
_REPO_ACTION = re.compile(r"^/v2/repository/models/([^/]+)/(load|unload)$")


class ModelServer:
    def __init__(self, name: str, engine: Optional[LLMEngine] = None, *,
                 repository=None,
                 tokenizer: Optional[Tokenizer] = None,
                 transformer=None,
                 explainer=None,
                 host: str = "127.0.0.1", port: int = 0,
                 grpc_port: Optional[int] = None):
        if (engine is None) == (repository is None):
            raise ValueError("pass exactly one of engine= or repository=")
        self.name = name                  # default model name
        self.engine = engine              # single-model mode only
        self.repository = repository
        self.tokenizer = tokenizer or get_tokenizer("byte")
        # Pre/post-processing hop (≈ kserve transformer — SURVEY.md §2.3):
        # transformer(text, phase) with phase in {"pre", "post"}.
        self.transformer = transformer
        # Explanation hop (≈ kserve explainer, the triad's third leg):
        # explainer(tokens, params=..., cfg=...) -> attribution dict,
        # served on the v1 :explain route (serve/explain.py).
        self.explainer = explainer
        self._in_flight = 0             # guarded_by: _in_flight_lock
        self._in_flight_lock = threading.Lock()
        handler = _make_handler(self)
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.httpd.daemon_threads = True
        quiet_handle_error(self.httpd)
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None
        # v2 protocol over gRPC as well as REST (grpc_port=0 → ephemeral).
        self.grpc_server = None
        if grpc_port is not None:
            from kubeflow_tpu.serve.grpc_server import GRPCInferenceServer

            self.grpc_server = GRPCInferenceServer(self, host=host,
                                                   port=grpc_port)

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> None:
        if self.engine is not None:
            self.engine.start()
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True, name="model-server")
        self._thread.start()
        if self.grpc_server is not None:
            self.grpc_server.start()

    def stop(self) -> None:
        from kubeflow_tpu.runtime.sanitize import assert_threads_quiescent

        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            # KFTPU_SANITIZE=threads: the serve thread must be dead now
            # (its target binds to httpd, so audit it explicitly).
            assert_threads_quiescent(threads=(self._thread,), grace_s=5.0)
            self._thread = None
        if self.grpc_server is not None:
            self.grpc_server.stop()
        if self.engine is not None:
            self.engine.stop()
        if self.repository is not None:
            self.repository.shutdown()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    # -- model resolution ------------------------------------------------------

    def model_names(self) -> list[str]:
        if self.repository is None:
            lora = getattr(self.engine, "_lora", None)
            if lora is not None:
                # Multi-tenant LoRA: every registered adapter is a
                # servable model id on this engine.
                return [self.name] + lora.names()
            return [self.name]
        return self.repository.names()

    def resolve_adapter(self, name: Optional[str]) -> Optional[str]:
        """Map a request's model id onto this server's LoRA surface:
        None/base name = base weights; a registered adapter name decodes
        through its packed slot; anything else on a LoRA-enabled engine
        is a 404 (KeyError) — multi-tenant serving must never silently
        fall a tenant through to the base model. LoRA-free servers
        return None (the pre-LoRA lease semantics apply)."""
        if self.repository is not None or name in (None, self.name):
            return None
        lora = getattr(self.engine, "_lora", None)
        if lora is None:
            return None
        if not lora.known(name):
            raise KeyError(
                f"unknown model {name!r}: not a registered adapter "
                f"(serving {self.name})")
        return name

    def lease(self, name: Optional[str], *, strict: bool = False):
        """Context manager: (engine, tokenizer, resolved_name) pinned for the
        request's duration (repository mode leases against LRU eviction).

        ``strict`` (path-addressed endpoints): a single-model server 404s a
        foreign name. Non-strict (OpenAI body "model" field): a foreign name
        is ignored — OpenAI SDK clients always send one, and the
        pre-multi-model server served them."""
        import contextlib

        if self.repository is None:
            if strict and name not in (None, self.name):
                raise KeyError(f"unknown model {name!r} (serving {self.name})")

            @contextlib.contextmanager
            def single():
                yield self.engine, self.tokenizer, self.name

            return single()

        @contextlib.contextmanager
        def leased():
            entry = self.repository.acquire(name or self.name)
            try:
                yield entry.engine, entry.tokenizer, entry.name
            finally:
                self.repository.release(entry)

        return leased()

    def model_config(self, name: str):
        """Model metadata without forcing a load."""
        if self.repository is None:
            if name != self.name:
                lora = getattr(self.engine, "_lora", None)
                if lora is not None and lora.known(name):
                    # An adapter id serves the base architecture.
                    return self.engine.cfg
                raise KeyError(name)
            return self.engine.cfg
        entry = self.repository.peek(name)
        if entry is None:
            raise KeyError(name)
        return entry.cfg

    def explain_text(self, prompt: str, model: Optional[str]) -> dict:
        """Tokenize → attribution handler → per-token scores with their
        decoded token strings (the v1 ``:explain`` payload)."""
        if self.explainer is None:
            raise ValueError("no explainer configured on this service")
        if self.transformer is not None:
            prompt = self.transformer(prompt, "pre")
        with self.lease(model, strict=True) as (engine, tokenizer, _):
            toks = tokenizer.encode(prompt)
            # Attribution is O(S) forwards (leave_one_out batches an [S+1,S]
            # block): an uncapped prompt would OOM the live serving chip.
            limit = min(engine.max_len, engine.cfg.max_seq_len)
            if len(toks) > limit:
                raise ValueError(
                    f"explain prompt is {len(toks)} tokens; limit {limit}")
            cfg = engine.cfg
            if cfg.is_moe and cfg.moe_impl != "dense":
                # Attribution must be batch-independent: dispatch MoE's
                # shared [E, C] capacity buffers couple co-batched rows
                # (leave_one_out's S ablations would perturb each other's
                # expert drops; grad_x_input's scores would depend on
                # capacity luck). Dense MoE routes every token exactly —
                # the same reason decode defaults to dense in the engine.
                import dataclasses as _dc
                cfg = _dc.replace(cfg, moe_impl="dense")
            # mesh: the TP engine's params are sharded (and possibly int8)
            # — the handlers jit with it so GSPMD partitions attribution
            # the same way it partitions serving dispatches.
            out = self.explainer(toks, params=engine.params, cfg=cfg,
                                 mesh=engine.mesh)
            out["tokens"] = [tokenizer.decode([t]) for t in toks]
            out["predicted_text"] = tokenizer.decode([out["target_token"]])
        return out

    def request_timeout(self, body: dict,
                        deadline_s: Optional[float] = None) -> float:
        """Effective per-request budget: the body ``timeout`` capped by the
        remaining client budget from the router's deadline header."""
        timeout = float(body.get("timeout", 300))
        if deadline_s is not None:
            timeout = min(timeout, max(deadline_s, 0.0))
        return timeout

    def generate_text(self, prompt: str, body: dict, model: Optional[str],
                      strict: bool = False,
                      deadline_s: Optional[float] = None,
                      qos: str = QOS_DEFAULT,
                      decode_url: Optional[str] = None,
                      decode_alts: tuple = ()) -> tuple[str, "Request"]:
        """Pre-hop → tokenize → engine → detokenize → post-hop: the one
        generation path every protocol surface (REST v1/v2, OpenAI, gRPC)
        shares.

        Lifecycle: the engine-side request carries a deadline equal to the
        client budget (``deadline_s`` from the router header, capped by the
        body timeout), so the scheduler reaps it — freeing its slot and KV
        pages — the moment the client can no longer use the answer. The
        result wait gets one extra second past that deadline so the normal
        path is the engine's explicit reap; the TimeoutError fallback (a
        wedged scheduler) cancels the orphan so a recovering engine drops
        it instead of decoding dead work."""
        if self.transformer is not None:
            prompt = self.transformer(prompt, "pre")
        timeout = self.request_timeout(body, deadline_s)
        tracer = get_tracer()
        # Multi-tenant LoRA: an adapter id leases the BASE engine and
        # decodes through the adapter's packed slot (resolve_adapter
        # 404s unknown ids on LoRA-enabled engines).
        adapter = self.resolve_adapter(model)
        with self.lease(None if adapter else model,
                        strict=strict) as (engine, tokenizer, _):
            toks = tokenizer.encode(prompt)
            # Disaggregated placement: on a prefill-role engine with a
            # router-stamped decode backend, stop at the first token and
            # hand the KV off; without one, decode locally (the
            # unified-fallback path).
            wants_handoff = engine.role == "prefill" and decode_url
            handoff_flag: Optional[bool] = None
            if engine.role == "prefill":
                handoff_flag = bool(wants_handoff)
            req = engine.submit(toks, self.sampling_from(body, tokenizer),
                                deadline=time.monotonic() + timeout,
                                trace_parent=tracer.current(), qos=qos,
                                handoff=handoff_flag, adapter=adapter)
            try:
                out = req.result(timeout=timeout + 1.0)
            except TimeoutError:
                req.cancel()
                raise
            if req.finish_reason == "handoff":
                text = self._relay_handoff_text(
                    engine, tokenizer, req, toks, body, decode_url,
                    qos=qos, timeout=timeout, decode_alts=decode_alts)
            else:
                _raise_for_reaped(req)
                with tracer.span("server.detokenize", tokens=len(out)):
                    text = tokenizer.decode(
                        [t for t in out if t != tokenizer.eos_id])
        if self.transformer is not None:
            text = self.transformer(text, "post")
        return text, req

    def _relay_handoff_text(self, engine, tokenizer, req, toks: list[int],
                            body: dict, decode_url: str, *, qos: str,
                            timeout: float, decode_alts: tuple = ()) -> str:
        """Non-streaming half of the handoff relay: POST the payload,
        join the decode replica's token pieces after the locally-sampled
        first token. Failure before the ack retries a DIFFERENT decode
        replica (router-stamped alternates, jittered backoff); exhausted
        alternates = recompute locally (handoff contract: failure costs
        a prefill, never the request)."""
        tracer = get_tracer()
        deadline = time.monotonic() + timeout
        candidates = [decode_url] + [u for u in decode_alts
                                     if u and u != decode_url]
        with tracer.span("engine.handoff", backend=decode_url,
                         request=req.id) as sp:
            try:
                used_url, conn, resp = open_handoff_with_retry(
                    engine, candidates, req.handoff, chat=False, qos=qos,
                    trace_fn=lambda: tracer.inject(sp),
                    deadline_s=timeout, timeout=timeout + 5.0)
                sp.set_attrs(backend=used_url)
                if used_url != decode_url:
                    # The placed decode replica died between pick and
                    # handoff; the fleet stitcher reads this event to
                    # attribute the hop as a failover, not a clean
                    # handoff.
                    sp.add_event("connect_failure", backend=decode_url)
            except OSError as exc:
                sp.set_attrs(error=str(exc), fallback="recompute")
                engine.metrics.note_handoff("fallback")
                engine.fail_handoff(req.id)
                return self._recompute_locally(engine, tokenizer, req,
                                               toks, body, qos=qos,
                                               timeout=timeout)
            engine.complete_handoff(req.id)
            # Collect raw token ids (the handoff SSE carries them) and
            # decode the WHOLE sequence once — piecewise decoding would
            # split multi-byte characters the unified path decodes
            # together.
            tokens = list(req.output_tokens)
            try:
                try:
                    for data in iter_sse_data(resp):
                        if data == "[DONE]":
                            break
                        choice = json.loads(data)["choices"][0]
                        tokens.append(int(choice["token"]))
                        if time.monotonic() > deadline + 1.0:
                            raise TimeoutError(
                                f"handoff relay for {req.id} exceeded "
                                "its deadline")
                finally:
                    conn.close()
            except (OSError, ValueError, KeyError) as exc:
                # Post-ack failure: the decode side died mid-stream. The
                # pages are gone (ack released them) and tokens may have
                # reached nobody — surface an explicit error.
                raise RuntimeError(
                    f"decode replica failed mid-handoff for {req.id}: "
                    f"{exc}") from exc
            sp.set_attrs(tokens=len(tokens))
        return tokenizer.decode(
            [t for t in tokens if t != tokenizer.eos_id])

    def _recompute_locally(self, engine, tokenizer, req, toks: list[int],
                           body: dict, *, qos: str, timeout: float) -> str:
        """Handoff failure = recompute: re-run the request as a unified
        local decode (the prefix cache usually makes the second prefill
        one admission)."""
        req2 = engine.submit(toks, self.sampling_from(body, tokenizer),
                             deadline=time.monotonic() + timeout,
                             trace_parent=get_tracer().current(), qos=qos,
                             handoff=False, request_id=f"{req.id}-recompute")
        try:
            out = req2.result(timeout=timeout + 1.0)
        except TimeoutError:
            req2.cancel()
            raise
        _raise_for_reaped(req2)
        return tokenizer.decode([t for t in out if t != tokenizer.eos_id])

    # -- request plumbing ------------------------------------------------------

    def track(self, delta: int) -> None:
        with self._in_flight_lock:
            self._in_flight += delta

    @property
    def in_flight(self) -> int:
        with self._in_flight_lock:
            return self._in_flight

    @staticmethod
    def sampling_from(body: dict[str, Any],
                      tokenizer: Tokenizer) -> SamplingParams:
        return SamplingParams(
            max_new_tokens=int(body.get("max_tokens", 64)),
            temperature=float(body.get("temperature", 0.0)),
            top_k=int(body.get("top_k", 0)),
            top_p=float(body.get("top_p", 1.0)),
            stop_token=tokenizer.eos_id,
        )

    def metrics_registry(self) -> MetricsRegistry:
        """Scrape-time registry over the live engine counters — the model
        server's half of the platform's single exposition path
        (obs/registry.py)."""
        engines: list[tuple[str, LLMEngine]] = []
        if self.engine is not None:
            engines.append((self.name, self.engine))
        elif self.repository is not None:
            # peek only: a metrics scrape must not touch LRU recency or
            # load anything.
            for item in self.repository.index():
                entry = self.repository.peek(item["name"])
                if entry is not None and entry.engine is not None:
                    engines.append((entry.name, entry.engine))
        return serving_metrics_registry(engines, in_flight=self.in_flight)

    def metrics_text(self) -> str:
        return self.metrics_registry().render()


def serving_metrics_registry(engines: list, *,
                             in_flight: int = 0) -> MetricsRegistry:
    """Build the serving ``/metrics`` registry for a set of ``(name,
    engine)`` pairs — the ONE definition of every ``kftpu_serving_*`` /
    ``kftpu_engine_*`` series. The model server scrapes through it, and
    the loadgen's direct-engine target renders the SAME exposition for
    its attribution join, so "engine-internal signals" always means the
    production series, never a parallel bookkeeping path."""
    reg = MetricsRegistry()
    requests_total = reg.counter("kftpu_serving_requests_total")
    tokens_total = reg.counter("kftpu_serving_tokens_total")
    reg.gauge("kftpu_serving_in_flight").set(in_flight)
    queue_depth = reg.gauge("kftpu_serving_queue_depth")
    shed = reg.counter("kftpu_serving_requests_shed_total")
    cancelled = reg.counter("kftpu_serving_requests_cancelled_total")
    expired = reg.counter("kftpu_serving_requests_expired_total")
    qdelay = reg.histogram("kftpu_serving_queue_delay_seconds",
                           QUEUE_DELAY_BUCKETS)
    # Multi-tenant QoS: per-class SLO attainment (the series the
    # signal-driven autoscaler weighs) + shed/preemption attribution.
    preempt = reg.counter("kftpu_serving_preemptions_total")
    qos_requests = reg.counter("kftpu_serving_qos_requests_total")
    qos_shed = reg.counter("kftpu_serving_qos_requests_shed_total")
    qos_preempt = reg.counter("kftpu_serving_qos_preemptions_total")
    qos_ttft = reg.gauge("kftpu_serving_qos_ttft_p95_ms")
    qos_qd = reg.gauge("kftpu_serving_qos_queue_delay_p95_ms")
    qos_qdelay = reg.histogram("kftpu_serving_qos_queue_delay_seconds",
                               QUEUE_DELAY_BUCKETS)
    # Decode hot-loop health (pipelined dispatch): per-round host gap
    # + how many rounds ride in flight. A pipelined engine shows
    # near-zero gaps and depth 1; gaps growing toward the round time
    # mean the host (detokenize/stream/admit) is the bottleneck again.
    host_gap = reg.histogram("kftpu_engine_host_gap_seconds",
                             HOST_GAP_BUCKETS)
    depth = reg.gauge("kftpu_engine_dispatch_depth")
    # Disaggregated serving: the token-aware router's placement signals
    # (pending prefill tokens → prefill pool, resident KV pages → decode
    # pool) plus the handoff lifecycle counters.
    pending_prefill = reg.gauge("kftpu_engine_pending_prefill_tokens")
    # Tiered KV cache: resident is split REFERENCED (live requests'
    # pages — real load, the decode router's placement signal) vs
    # CACHED (ref-0 reclaimable prefix content — freely evictable, so
    # capacity, not load), plus the host-RAM overflow tier's occupancy
    # and the radix/tier lifecycle counters (serve/kvtier.py).
    pages_resident = reg.gauge("kftpu_engine_kv_pages_resident")
    pages_cached = reg.gauge("kftpu_engine_kv_pages_cached")
    pages_host = reg.gauge("kftpu_engine_kv_pages_host")
    prefix_hits = reg.counter("kftpu_engine_kv_prefix_hits_total")
    prefix_tokens = reg.counter("kftpu_engine_kv_prefix_tokens_reused_total")
    cow_copies = reg.counter("kftpu_engine_kv_cow_copies_total")
    pages_demoted = reg.counter("kftpu_engine_kv_pages_demoted_total")
    pages_promoted = reg.counter("kftpu_engine_kv_pages_promoted_total")
    handoffs_out = reg.counter("kftpu_engine_handoffs_exported_total")
    handoffs_in = reg.counter("kftpu_engine_handoffs_adopted_total")
    handoffs_bad = reg.counter("kftpu_engine_handoffs_failed_total")
    # Fleet-wide KV fabric (ISSUE 17): the remote third tier's occupancy
    # and store traffic, its degrade paths (deadline/corrupt — each one
    # is a request that RESOLVED via recompute), the tier-pressure ratio
    # the autoscaler folds, and the cross-host handoff failure budget
    # (retried = moved to another decode replica; fallback = recomputed
    # locally after exhausting them).
    pages_remote = reg.gauge("kftpu_engine_kv_pages_remote")
    remote_demote_b = reg.counter(
        "kftpu_engine_kv_remote_demoted_bytes_total")
    remote_promote_b = reg.counter(
        "kftpu_engine_kv_remote_promoted_bytes_total")
    remote_timeouts = reg.counter(
        "kftpu_engine_kv_remote_promote_timeouts_total")
    remote_corrupt = reg.counter(
        "kftpu_engine_kv_remote_blobs_corrupt_total")
    tier_pressure = reg.gauge("kftpu_engine_kv_tier_pressure")
    handoffs_retried = reg.counter("kftpu_engine_handoffs_retried_total")
    handoffs_fb = reg.counter("kftpu_engine_handoffs_fallback_total")
    # Quantized KV fabric (ops/quantization.py kv path): whether the
    # pool stores int8, the pool's token density (the ~1.9x-at-equal-HBM
    # claim's series), and the actual wire bytes moved by handoff export/
    # adopt and tier demote/promote — int8+scales blobs read ~half the
    # full-dtype bytes, and THESE counters are where that shows up.
    kvq_enabled = reg.gauge("kftpu_engine_kv_quant_enabled")
    kvq_density = reg.gauge("kftpu_engine_kv_quant_tokens_per_mib")
    ho_bytes_out = reg.counter("kftpu_engine_kv_handoff_bytes_exported_total")
    ho_bytes_in = reg.counter("kftpu_engine_kv_handoff_bytes_adopted_total")
    wire_demote = reg.counter("kftpu_engine_kv_wire_bytes_demoted_total")
    wire_promote = reg.counter("kftpu_engine_kv_wire_bytes_promoted_total")
    # Multi-tenant LoRA (serve/lora.py): which adapters are HOT on this
    # engine (one ``adapter=``-labeled sample per resident adapter — the
    # model-id router's placement signal; a 0 sample without the label
    # when none are) plus the hot-load/evict lifecycle counters.
    adapters_resident = reg.gauge("kftpu_engine_adapters_resident")
    adapter_loads = reg.counter("kftpu_engine_adapter_loads_total")
    adapter_evictions = reg.counter("kftpu_engine_adapter_evictions_total")
    for name, engine in engines:
        snap = engine.metrics.snapshot()
        requests_total.inc(snap["requests_completed"], model=name)
        tokens_total.inc(snap["tokens_generated"], model=name)
        for k in ("ttft_p50_ms", "ttft_p95_ms", "ttft_p99_ms",
                  "tpot_p50_ms", "queue_delay_p95_ms",
                  "requests_per_sec", "tokens_per_sec",
                  "spec_acceptance_rate", "spec_tokens_per_step",
                  "spec_draft_overhead", "host_gap_p50_ms",
                  "host_gap_p99_ms"):
            if k in snap:
                reg.gauge(f"kftpu_serving_{k}").set(snap[k], model=name)
        # Load-shedding / lifecycle surface: queue depth, shed and reap
        # counters, and the queue-delay histogram — the dashboards that
        # show an overload knee BEFORE clients start timing out.
        queue_depth.set(engine.queue_depth(), model=name)
        shed.inc(snap["requests_shed"], model=name)
        cancelled.inc(snap["requests_cancelled"], model=name)
        expired.inc(snap["requests_expired"], model=name)
        _, counts, qsum, qn = engine.metrics.queue_delay_histogram()
        qdelay.set_cumulative(counts, qsum, qn, model=name)
        preempt.inc(snap.get("preemptions", 0), model=name)
        for cls, c in snap.get("qos", {}).items():
            qos_requests.inc(c["completed"], model=name, qos=cls)
            qos_shed.inc(c["shed"], model=name, qos=cls)
            qos_preempt.inc(c["preempted"], model=name, qos=cls)
            if "ttft_p95_ms" in c:
                qos_ttft.set(c["ttft_p95_ms"], model=name, qos=cls)
            if "queue_delay_p95_ms" in c:
                qos_qd.set(c["queue_delay_p95_ms"], model=name, qos=cls)
            _, ccounts, csum, cn = \
                engine.metrics.queue_delay_histogram(cls)
            qos_qdelay.set_cumulative(ccounts, csum, cn,
                                      model=name, qos=cls)
        _, hcounts, hsum, hn = engine.metrics.host_gap_histogram()
        host_gap.set_cumulative(hcounts, hsum, hn, model=name)
        depth.set(snap.get("dispatch_depth", 0), model=name)
        pending_prefill.set(engine.pending_prefill_tokens(), model=name)
        pages_resident.set(engine.kv_pages_in_use(), model=name)
        pages_cached.set(engine.kv_pages_cached(), model=name)
        pages_host.set(engine.kv_pages_host(), model=name)
        tier = engine.kv_tier_stats()
        prefix_hits.inc(tier.get("prefix_hits", 0), model=name)
        prefix_tokens.inc(tier.get("tokens_matched", 0), model=name)
        cow_copies.inc(tier.get("cow_copies", 0), model=name)
        pages_demoted.inc(tier.get("pages_demoted", 0), model=name)
        pages_promoted.inc(tier.get("pages_promoted", 0), model=name)
        handoffs_out.inc(snap.get("handoffs_exported", 0), model=name)
        handoffs_in.inc(snap.get("handoffs_adopted", 0), model=name)
        handoffs_bad.inc(snap.get("handoffs_failed", 0), model=name)
        handoffs_retried.inc(snap.get("handoffs_retried", 0), model=name)
        handoffs_fb.inc(snap.get("handoffs_fallback", 0), model=name)
        pages_remote.set(engine.kv_pages_remote(), model=name)
        remote_demote_b.inc(tier.get("remote_demote_bytes", 0), model=name)
        remote_promote_b.inc(tier.get("remote_promote_bytes", 0),
                             model=name)
        remote_timeouts.inc(tier.get("remote_promote_timeouts", 0),
                            model=name)
        remote_corrupt.inc(tier.get("remote_blobs_corrupt", 0), model=name)
        tier_pressure.set(round(engine.kv_tier_pressure(), 3), model=name)
        # Contiguous-cache engines render 0/0: the series must exist on
        # every replica (the loadgen attribution scrape pins the set).
        density = engine.kv_pool_density()
        kvq_enabled.set(density.get("quant", 0), model=name)
        kvq_density.set(round(density.get("tokens_per_mib", 0.0), 1),
                        model=name)
        ho_bytes_out.inc(snap.get("handoff_bytes_exported", 0), model=name)
        ho_bytes_in.inc(snap.get("handoff_bytes_adopted", 0), model=name)
        wire_demote.inc(tier.get("demote_wire_bytes", 0), model=name)
        wire_promote.inc(tier.get("promote_wire_bytes", 0), model=name)
        resident = engine.adapters_resident()
        for a in resident:
            adapters_resident.set(1, model=name, adapter=a)
        if not resident:
            adapters_resident.set(0, model=name)
        astats = engine.adapter_stats()
        adapter_loads.inc(astats.get("loads", 0), model=name)
        adapter_evictions.inc(astats.get("evictions", 0), model=name)
    return reg


def _make_handler(server: ModelServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *args) -> None:  # quiet
            pass

        # -- helpers ----------------------------------------------------------

        def _json(self, code: int, obj: Any,
                  headers: Optional[dict] = None) -> None:
            data = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)

        def _deadline_s(self) -> Optional[float]:
            """Remaining client budget (seconds) from the router's deadline
            header; None when the request carries no deadline."""
            hdr = self.headers.get(DEADLINE_HEADER)
            contract_note_header(DEADLINE_HEADER, direction="read")
            if not hdr:
                return None
            try:
                return max(float(hdr) / 1e3, 0.0)
            except ValueError:
                return None

        def _text(self, code: int, text: str, ctype="text/plain") -> None:
            data = text.encode()
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _body(self) -> dict:
            n = int(self.headers.get("Content-Length", 0))
            return json.loads(self.rfile.read(n) or b"{}")

        # -- GET ---------------------------------------------------------------

        def do_GET(self) -> None:
            if self.path in ("/healthz", "/v2/health/ready", "/v2/health/live"):
                self._json(200, {"status": "ok", "name": server.name})
                return
            if self.path == "/metrics":
                self._text(200, server.metrics_text())
                return
            if self.path.startswith("/debug/traces"):
                return self._json(200, debug_traces_payload(self.path))
            if self.path.startswith("/debug/spans/export"):
                # Fleet-trace drain (obs/fleet.py): completed spans +
                # this process's clock, for cross-host stitching.
                return self._json(200, spans_export_payload(
                    process=f"server:{server.name}"))
            if self.path == "/v1/models":
                self._json(200, {"models": server.model_names()})
                return
            if self.path == "/v2/repository/index":
                if server.repository is None:
                    self._json(200, {"models": [
                        {"name": server.name, "state": "READY"}]})
                else:
                    self._json(200, {"models": server.repository.index()})
                return
            m = _V2_MODEL.match(self.path)
            if m:
                try:
                    cfg = server.model_config(m.group(1))
                except KeyError:
                    return self._json(404, {"error": f"no model {m.group(1)}"})
                self._json(200, {
                    "name": m.group(1),
                    "platform": "kubeflow-tpu-llm",
                    "inputs": [{"name": "text", "datatype": "BYTES",
                                "shape": [-1]}],
                    "outputs": [{"name": "text", "datatype": "BYTES",
                                 "shape": [-1]}],
                    "config": {"vocab_size": cfg.vocab_size,
                               "max_seq_len": cfg.max_seq_len},
                })
                return
            self._json(404, {"error": f"not found: {self.path}"})

        # -- POST --------------------------------------------------------------

        def do_POST(self) -> None:
            server.track(1)
            tracer = get_tracer()
            contract_note_header(TRACE_HEADER, direction="read")
            try:
                # Joins the router's trace via X-Kftpu-Trace (or roots a new
                # one for direct-to-replica requests); every generation path
                # below parents its engine-side spans on this span through
                # the contextvar.
                with tracer.span(
                        "server.request",
                        parent=tracer.extract(
                            self.headers.get(TRACE_HEADER)),
                        path=self.path, server=server.name):
                    if self.path.split("?", 1)[0] == "/v1/handoff":
                        # Binary payload — must not ride the JSON drain.
                        return self._handoff()
                    # Always drain the body first: HTTP/1.1 keep-alive
                    # breaks if unread bytes remain on the connection.
                    body = self._body()
                    repo = _REPO_ACTION.match(self.path)
                    if repo:
                        return self._repository_action(repo.group(1),
                                                       repo.group(2))
                    m = _V1_PREDICT.match(self.path)
                    if m:
                        return self._v1_predict(body, m.group(1))
                    m = _V1_EXPLAIN.match(self.path)
                    if m:
                        return self._v1_explain(body, m.group(1))
                    m = _V2_INFER.match(self.path)
                    if m:
                        return self._v2_infer(body, m.group(1))
                    if self.path == "/v1/completions":
                        return self._completions(body, chat=False)
                    if self.path == "/v1/chat/completions":
                        return self._completions(body, chat=True)
                    self._json(404, {"error": f"not found: {self.path}"})
            except KeyError as exc:
                self._json(404, {"error": str(exc)})
            except ValueError as exc:
                self._json(400, {"error": str(exc)})
            except EngineOverloaded as exc:
                # Bounded admission: shed fast with an explicit retry hint
                # instead of queueing the client into a timeout.
                self._json(429, {"error": str(exc)}, headers={
                    "Retry-After": str(max(1, int(exc.retry_after)))})
            except TimeoutError as exc:
                self._json(504, {"error": str(exc)})
            except Exception as exc:   # surface, don't hide
                self._json(500, {"error": f"{type(exc).__name__}: {exc}"})
            finally:
                server.track(-1)

        def _repository_action(self, name: str, action: str) -> None:
            if server.repository is None:
                return self._json(400, {"error": "single-model server"})
            if action == "load":
                server.repository.load(name)
            else:
                server.repository.unload(name)
            self._json(200, {"name": name, "state": "READY"
                             if action == "load" else "UNLOADED"})

        def _qos(self, body: dict) -> str:
            """QoS class from the ``X-Kftpu-Qos`` header (body ``qos``
            field as the headerless fallback). Unknown classes fail loudly
            (engine.submit raises → HTTP 400) rather than silently
            demoting a tenant to the default tier."""
            contract_note_header(QOS_HEADER, direction="read")
            raw = self.headers.get(QOS_HEADER) or body.get("qos") \
                or QOS_DEFAULT
            return str(raw).strip().lower()

        def _decode_backend(self) -> Optional[str]:
            """Decode-pool backend the token-aware router picked for this
            request's KV handoff (absent = unified local decode)."""
            contract_note_header(DECODE_BACKEND_HEADER, direction="read")
            url = self.headers.get(DECODE_BACKEND_HEADER)
            return url.strip() if url else None

        def _decode_alts(self) -> tuple:
            """Alternate decode backends for the handoff's bounded
            cross-replica retry (router-stamped; absent = no retry)."""
            contract_note_header(DECODE_ALTS_HEADER, direction="read")
            raw = self.headers.get(DECODE_ALTS_HEADER) or ""
            return tuple(u.strip() for u in raw.split(",") if u.strip())

        def _generate_text(self, prompt: str, body: dict,
                           model: Optional[str],
                           strict: bool = False) -> tuple[str, Request]:
            return server.generate_text(prompt, body, model, strict=strict,
                                        deadline_s=self._deadline_s(),
                                        qos=self._qos(body),
                                        decode_url=self._decode_backend(),
                                        decode_alts=self._decode_alts())

        def _v1_predict(self, body: dict, model: str) -> None:
            instances = body.get("instances")
            if not isinstance(instances, list):
                raise ValueError("body must contain 'instances': [...]")
            preds = [self._generate_text(str(inst), body, model,
                                         strict=True)[0]
                     for inst in instances]
            self._json(200, {"predictions": preds})

        def _v1_explain(self, body: dict, model: str) -> None:
            instances = body.get("instances")
            if not isinstance(instances, list):
                raise ValueError("body must contain 'instances': [...]")
            exps = [server.explain_text(str(inst), model)
                    for inst in instances]
            self._json(200, {"explanations": exps})

        def _v2_infer(self, body: dict, model: str) -> None:
            inputs = body.get("inputs")
            if not isinstance(inputs, list) or not inputs:
                raise ValueError("body must contain 'inputs': [...]")
            texts = []
            for inp in inputs:
                for datum in inp.get("data", []):
                    texts.append(self._generate_text(str(datum), body,
                                                     model, strict=True)[0])
            self._json(200, {
                "model_name": model,
                "outputs": [{"name": "text", "datatype": "BYTES",
                             "shape": [len(texts)], "data": texts}],
            })

        def _model_id(self, body: dict) -> Optional[str]:
            """Requested model id: the X-Kftpu-Model header (the fleet
            router's routing key) wins; the OpenAI ``"model"`` body
            field is the headerless fallback."""
            contract_note_header(MODEL_HEADER, direction="read")
            hdr = self.headers.get(MODEL_HEADER)
            return hdr.strip() if hdr else body.get("model")

        def _completions(self, body: dict, *, chat: bool) -> None:
            model = self._model_id(body)
            if chat:
                msgs = body.get("messages", [])
                prompt = "\n".join(f"{m.get('role', 'user')}: {m.get('content', '')}"
                                   for m in msgs) + "\nassistant:"
            else:
                prompt = body.get("prompt", "")
                if isinstance(prompt, list):
                    prompt = prompt[0] if prompt else ""
            if body.get("stream"):
                return self._completions_stream(prompt, body, chat=chat,
                                                model=model)
            text, req = self._generate_text(prompt, body, model)
            usage = {"prompt_tokens": len(req.prompt_tokens),
                     "completion_tokens": len(req.output_tokens),
                     "total_tokens": len(req.prompt_tokens) + len(req.output_tokens)}
            if chat:
                choice = {"index": 0, "finish_reason": req.finish_reason,
                          "message": {"role": "assistant", "content": text}}
                obj = "chat.completion"
            else:
                choice = {"index": 0, "finish_reason": req.finish_reason,
                          "text": text}
                obj = "text_completion"
            self._json(200, {
                "id": req.id, "object": obj, "created": int(time.time()),
                "model": model or server.name, "choices": [choice],
                "usage": usage,
            })

        def _send_sse_headers(self) -> None:
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

        def _chunk(self, data: str) -> None:
            payload = f"data: {data}\n\n".encode()
            self.wfile.write(f"{len(payload):x}\r\n".encode()
                             + payload + b"\r\n")
            self.wfile.flush()

        def _stream_tokens(self, req, tokenizer, *, chat: bool,
                           model: Optional[str], timeout: float,
                           with_token_ids: bool = False) -> None:
            """Send SSE headers and stream one engine request's tokens
            to the client (the local-decode half of every streaming
            path: unified, decode-side adoption, and the recompute
            fallback). ``with_token_ids`` adds the raw token id to each
            chunk — the handoff relay uses it so a non-streaming caller
            can re-decode the WHOLE sequence at once (piecewise byte
            decoding would mangle multi-byte characters)."""
            self._send_sse_headers()
            try:
                while True:
                    try:
                        tok = req.stream.get(timeout=timeout + 1.0)
                    except queue.Empty:
                        # Engine never finished within the deadline
                        # (its own reaper should have; this is the
                        # wedged-scheduler fallback): cancel so a
                        # recovering engine drops the orphan.
                        req.cancel()
                        break
                    if tok is None:
                        break
                    if tok == tokenizer.eos_id:
                        continue
                    piece = tokenizer.decode([tok])
                    if chat:
                        delta = {"choices": [
                            {"index": 0, "delta": {"content": piece}}]}
                    else:
                        delta = {"choices": [{"index": 0,
                                              "text": piece}]}
                    if with_token_ids:
                        delta["choices"][0]["token"] = tok
                    self._chunk(json.dumps({"id": req.id, "object": "chunk",
                                            "model": model or server.name,
                                            **delta}))
            except OSError:
                # Client hung up mid-stream: free the slot and its KV
                # pages now instead of decoding to completion for a
                # reader that is gone.
                req.cancel()
                self.close_connection = True
                return
            self._chunk("[DONE]")
            self.wfile.write(b"0\r\n\r\n")

        def _completions_stream(self, prompt: str, body: dict, *, chat: bool,
                                model: Optional[str]) -> None:
            # The pre-hook applies to the prompt like the non-streaming path;
            # the post-hook cannot (output streams piecewise) — a documented
            # transformer limitation, matching kserve's non-streaming scope.
            if server.transformer is not None:
                prompt = server.transformer(prompt, "pre")
            timeout = server.request_timeout(body, self._deadline_s())
            adapter = server.resolve_adapter(model)
            with server.lease(None if adapter else model) \
                    as (engine, tokenizer, _):
                toks = tokenizer.encode(prompt)
                decode_url = self._decode_backend()
                wants_handoff = engine.role == "prefill" and decode_url
                handoff_flag: Optional[bool] = None
                if engine.role == "prefill":
                    handoff_flag = bool(wants_handoff)
                req = engine.submit(toks,
                                    server.sampling_from(body, tokenizer),
                                    deadline=time.monotonic() + timeout,
                                    trace_parent=get_tracer().current(),
                                    qos=self._qos(body),
                                    handoff=handoff_flag, adapter=adapter)
                if wants_handoff:
                    return self._stream_disaggregated(
                        engine, tokenizer, req, toks, body, decode_url,
                        chat=chat, model=model, timeout=timeout,
                        decode_alts=self._decode_alts())
                self._stream_tokens(req, tokenizer, chat=chat, model=model,
                                    timeout=timeout)

        def _stream_disaggregated(self, engine, tokenizer, req,
                                  toks: list[int], body: dict,
                                  decode_url: str, *, chat: bool,
                                  model: Optional[str],
                                  timeout: float,
                                  decode_alts: tuple = ()) -> None:
            """Streaming handoff relay. The client's SSE response opens
            only AFTER the decode side acks (or the fallback engages) —
            a prefill replica dying mid-handoff therefore dies with
            ZERO response bytes on the wire, which is exactly the
            condition under which the router's connect-failure retry
            can requeue the request onto a surviving pool."""
            tracer = get_tracer()
            if not req.done.wait(timeout + 1.0):
                req.cancel()
                return self._json(504, {"error": f"request {req.id} timed "
                                        "out in prefill"})
            if req.finish_reason != "handoff":
                # Finished at the first token (stop/length) — nothing to
                # hand off; stream the one-token answer. Reap failures
                # surface through the usual mapping.
                if req.finish_reason in ("stop", "length"):
                    return self._stream_tokens(req, tokenizer, chat=chat,
                                               model=model, timeout=timeout)
                _raise_for_reaped(req)
                raise RuntimeError(
                    f"request {req.id} ended {req.finish_reason!r}")
            candidates = [decode_url] + [u for u in decode_alts
                                         if u and u != decode_url]
            with tracer.span("engine.handoff", backend=decode_url,
                             request=req.id) as sp:
                try:
                    used_url, conn, resp = open_handoff_with_retry(
                        engine, candidates, req.handoff, chat=chat,
                        qos=self._qos(body),
                        trace_fn=lambda: tracer.inject(sp),
                        deadline_s=timeout, timeout=timeout + 5.0)
                    sp.set_attrs(backend=used_url)
                    if used_url != decode_url:
                        # Placed decode replica died between pick and
                        # handoff — mark the span so the fleet stitcher
                        # attributes this hop as a failover.
                        sp.add_event("connect_failure",
                                     backend=decode_url)
                except OSError as exc:
                    # Every replica exhausted, never acked: recompute
                    # locally (failure = recompute, never a drop).
                    sp.set_attrs(error=str(exc), fallback="recompute")
                    engine.metrics.note_handoff("fallback")
                    engine.fail_handoff(req.id)
                    req2 = engine.submit(
                        toks, server.sampling_from(body, tokenizer),
                        deadline=time.monotonic() + timeout,
                        trace_parent=tracer.current(),
                        qos=self._qos(body), handoff=False,
                        request_id=f"{req.id}-recompute")
                    return self._stream_tokens(req2, tokenizer, chat=chat,
                                               model=model, timeout=timeout)
                engine.complete_handoff(req.id)
            self._send_sse_headers()
            try:
                # First token was sampled prefill-side; its chunk opens
                # the client stream, then decode chunks relay verbatim.
                first = [t for t in req.output_tokens
                         if t != tokenizer.eos_id]
                if first:
                    piece = tokenizer.decode(first)
                    delta = ({"choices": [{"index": 0,
                                           "delta": {"content": piece}}]}
                             if chat else
                             {"choices": [{"index": 0, "text": piece}]})
                    self._chunk(json.dumps({"id": req.id, "object": "chunk",
                                            "model": model or server.name,
                                            **delta}))
                done = False
                try:
                    for data in iter_sse_data(resp):
                        self._chunk(data)
                        if data == "[DONE]":
                            done = True
                            break
                finally:
                    conn.close()
                if done:
                    self.wfile.write(b"0\r\n\r\n")
                    return
                # Upstream ended without [DONE]: the decode side died
                # mid-stream — close so the client sees an explicit error.
                self.close_connection = True
            except OSError:
                self.close_connection = True

        def _handoff(self) -> None:
            """Decode side of the handoff: adopt the payload into this
            engine's pool and stream the SECOND token onward as SSE.
            Sending the 200 response line IS the ack — the payload bytes
            are in this process's memory, so the prefill side's page
            hold can release."""
            if server.engine is None:
                return self._json(
                    400, {"error": "handoff needs a single-engine server"})
            from kubeflow_tpu.serve.handoff import HandoffPayload

            # Capability negotiation BEFORE touching the wire blob
            # (ISSUE 17): a mixed-version or mixed-dtype fleet must
            # reject the submit cleanly — an explicit 409 the prefill
            # side turns into retry-elsewhere/recompute — never decode
            # bytes it would misinterpret into corrupt pages.
            contract_note_header(HANDOFF_WIRE_HEADER, direction="read")
            contract_note_header(HANDOFF_DTYPE_HEADER, direction="read")
            wire_v = (self.headers.get(HANDOFF_WIRE_HEADER) or "").strip()
            if wire_v and wire_v not in SUPPORTED_HANDOFF_WIRE:
                return self._json(409, {
                    "error": f"handoff wire version {wire_v!r} not "
                             f"supported (speaks {SUPPORTED_HANDOFF_WIRE})"})
            dtype = (self.headers.get(HANDOFF_DTYPE_HEADER) or "").strip()
            want = "int8" if server.engine.kv_quant else "full"
            if dtype and dtype != want:
                return self._json(409, {
                    "error": f"handoff cache-dtype mismatch: payload is "
                             f"{dtype!r}, this pool stores {want!r}"})
            n = int(self.headers.get("Content-Length", 0))
            raw = self.rfile.read(n)
            chat = "chat=1" in (self.path.split("?", 1) + [""])[1]
            payload = HandoffPayload.from_wire(raw)
            deadline_s = self._deadline_s()
            timeout = deadline_s if deadline_s is not None else 300.0
            try:
                req = server.engine.submit_handoff(
                    payload, deadline=time.monotonic() + timeout,
                    trace_parent=get_tracer().current())
            except ValueError as exc:
                # submit_handoff's own validation (shape/dtype/deadline)
                # is the headerless fleet's backstop — same clean reject.
                return self._json(409, {"error": str(exc)})
            self._stream_tokens(req, server.tokenizer, chat=chat,
                                model=None, timeout=timeout,
                                with_token_ids=True)

    return Handler
