"""Serving stack: continuous-batching LLM engine, model servers, and the
InferenceService controller (SURVEY.md §2.3, §7 phase 5 — the KServe analog:
(U) kserve python/kserve ModelServer + python/huggingfaceserver vLLM runtime,
rebuilt TPU-native on a JAX decode engine)."""
