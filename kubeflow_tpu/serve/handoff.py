"""Paged-KV handoff between prefill- and decode-specialized engines —
the transfer contract of disaggregated serving (ROADMAP item 2; the
DistServe/Splitwise motif, TPU-native).

Prefill and decode have opposite compute profiles: prefill is
FLOPs-bound (one big causal block over the prompt), decode is
HBM-bandwidth-bound (one token per step against the whole KV). A
unified engine interleaves them on one chip, so a long prefill
head-of-line-blocks every resident decode stream's tokens. Splitting
the fleet into role-specialized pools removes that interference — IF
the prompt's KV can move from the prefill chip to the decode chip. The
page is the natural transfer unit: the prefill side exports the slot's
pages (one batched device→host fetch per admit round), the decode side
adopts them into its OWN ``PageAllocator`` pool (alloc + scatter upload
+ page-table row rebuild, ``owner=`` stamped so
``KFTPU_SANITIZE=refcount`` attributes leaks across the boundary).

Ownership protocol (who owns pages when):

1. **Export** (prefill engine, scheduler thread): the first token is
   sampled, the slot's KV is fetched to host, and the slot is freed —
   but its page references move to a HOLD keyed by request id, not to
   the free list. The payload is now host memory; the pages back it
   until the decode side confirms receipt.
2. **Ack** (prefill model server): the decode replica answered the
   handoff POST — the payload bytes are in its memory — so the hold is
   released (``engine.complete_handoff``). The release is marshalled
   through a queue onto the scheduler thread; the allocator stays
   single-owner.
3. **Failure = recompute**: if the decode side never acks (connect
   failure, 5xx, death mid-POST), ``engine.fail_handoff`` frees the
   hold and the model server re-submits the request LOCALLY as a
   unified request — the prefix cache usually makes the recompute one
   admission. A hold whose request is cancelled or past its deadline is
   reaped by the scheduler like any abandoned request, so a killed
   server can never strand pages (the mid-handoff SIGKILL chaos
   scenario audits exactly this).

Adoption seeds the decode slot at the exact state ``_admit_with_token``
would have left it: ``length = plen`` (the prompt's KV is written; the
first token's is not), ``last_token = first_token``, and the request's
``prompt_tokens`` carry ``prompt + [first_token]`` so recompute
preemption and speculative context reconstruction keep their
invariants. Greedy outputs are therefore token-identical to the unified
path on dense and paged backends (pinned in tests).

Wire format: one JSON metadata line + raw little-endian KV bytes
(dtype/shape in the metadata — bf16 rides as raw ml_dtypes bytes, no
pickle). Rides ``POST /v1/handoff`` with the usual ``X-Kftpu-*``
headers, so a handed-off request keeps ONE trace with a new ``handoff``
phase between ``prefill`` and the decode side's ``queued``/``decode``.

Wire format v2 (int8 KV pools, ``kv_cache_dtype="int8"``): the metadata
gains a ``cache_dtype`` tag plus ``scale_dtype``/``scale_shape``, and the
per-token-per-head f32 scale blobs ride after the page bytes —
``K + V + scale_K + scale_V``. A v1 blob carries no tag and decodes
exactly as before (scales come back ``None``), so mixed-dtype fleets
interoperate during a rollout: the adopting side rejects a cache-dtype
mismatch explicitly instead of misreading bytes. int8 payloads are the
wire-bytes win the bench rounds measure: ~half the KV bytes per handoff
and per host-tier demotion at 4/Dh scale overhead.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional

import numpy as np


def _np_dtype(name: str) -> np.dtype:
    """Resolve a dtype name, including the ml_dtypes extras (bfloat16)
    numpy itself does not know."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


@dataclasses.dataclass
class HandoffPayload:
    """One prefilled request's transferable state: identity + sampling
    contract + the prompt's KV as contiguous host arrays
    ``[L, plen, KV, Dh]`` (page structure is re-imposed by the adopting
    pool — its page size, its free list, its refcounts)."""

    request_id: str
    prompt_tokens: list[int]        # the plen tokens whose KV rides along
    first_token: int                # sampled on the prefill side (TTFT)
    max_new_tokens: int             # REMAINING decode budget (>= 1)
    temperature: float
    top_k: int
    top_p: float
    stop_token: Optional[int]
    qos: str
    kv_k: np.ndarray
    kv_v: np.ndarray
    # int8 pools only (wire v2): per-token-per-head f32 scales
    # ``[L, plen, KV]`` — kv shape minus head_dim (quantize_kv layout).
    kv_scale_k: Optional[np.ndarray] = None
    kv_scale_v: Optional[np.ndarray] = None

    @property
    def kv_len(self) -> int:
        return int(self.kv_k.shape[1])

    @property
    def cache_dtype(self) -> Optional[str]:
        """"int8" when scales ride along; None = full-dtype KV."""
        return "int8" if self.kv_scale_k is not None else None

    @property
    def wire_bytes(self) -> int:
        """KV payload bytes as they ride the wire (pages + scale blobs,
        metadata line excluded) — the handoff wire-bytes series' source,
        computed without re-encoding."""
        n = self.kv_k.nbytes + self.kv_v.nbytes
        if self.kv_scale_k is not None:
            n += self.kv_scale_k.nbytes + self.kv_scale_v.nbytes
        return n

    def validate(self) -> None:
        if self.kv_k.shape != self.kv_v.shape:
            raise ValueError("kv_k/kv_v shape mismatch")
        if self.kv_k.ndim != 4:
            raise ValueError(
                f"KV must be [L, plen, KV, Dh]; got {self.kv_k.shape}")
        if self.kv_len != len(self.prompt_tokens):
            raise ValueError(
                f"KV covers {self.kv_len} positions but the payload "
                f"names {len(self.prompt_tokens)} prompt tokens")
        if self.max_new_tokens < 1:
            raise ValueError("handoff with no decode budget left")
        if (self.kv_scale_k is None) != (self.kv_scale_v is None):
            raise ValueError("kv scale blobs must come as a pair")
        if self.kv_scale_k is not None:
            if self.kv_k.dtype != np.int8:
                raise ValueError(
                    "scale blobs ride only with int8 KV pages; got "
                    f"{self.kv_k.dtype}")
            want = self.kv_k.shape[:-1]
            if (self.kv_scale_k.shape != want
                    or self.kv_scale_v.shape != want):
                raise ValueError(
                    f"scale shape must be KV shape minus head_dim {want}; "
                    f"got {self.kv_scale_k.shape}/{self.kv_scale_v.shape}")

    # -- wire format -------------------------------------------------------

    def to_wire(self) -> bytes:
        """JSON metadata line + raw K bytes + raw V bytes (+ scale K/V
        bytes when the pool is int8 — wire v2)."""
        k = np.ascontiguousarray(self.kv_k)
        v = np.ascontiguousarray(self.kv_v)
        meta = {
            "request_id": self.request_id,
            "prompt_tokens": list(self.prompt_tokens),
            "first_token": int(self.first_token),
            "max_new_tokens": int(self.max_new_tokens),
            "temperature": float(self.temperature),
            "top_k": int(self.top_k),
            "top_p": float(self.top_p),
            "stop_token": self.stop_token,
            "qos": self.qos,
            "dtype": str(k.dtype),
            "shape": list(k.shape),
        }
        blob = k.tobytes() + v.tobytes()
        if self.kv_scale_k is not None:
            sk = np.ascontiguousarray(self.kv_scale_k, np.float32)
            sv = np.ascontiguousarray(self.kv_scale_v, np.float32)
            meta["cache_dtype"] = "int8"
            meta["scale_dtype"] = str(sk.dtype)
            meta["scale_shape"] = list(sk.shape)
            blob += sk.tobytes() + sv.tobytes()
        return json.dumps(meta).encode() + b"\n" + blob

    @classmethod
    def from_wire(cls, data: bytes) -> "HandoffPayload":
        head, sep, raw = data.partition(b"\n")
        if not sep:
            raise ValueError("handoff payload missing metadata line")
        meta = json.loads(head)
        dtype = _np_dtype(meta["dtype"])
        shape = tuple(int(x) for x in meta["shape"])
        n = int(np.prod(shape)) * dtype.itemsize
        sk = sv = None
        sn = 0
        if meta.get("cache_dtype") is not None:
            sdtype = _np_dtype(meta["scale_dtype"])
            sshape = tuple(int(x) for x in meta["scale_shape"])
            sn = int(np.prod(sshape)) * sdtype.itemsize
        if len(raw) != 2 * n + 2 * sn:
            raise ValueError(
                f"handoff payload truncated: {len(raw)} KV bytes, "
                f"expected {2 * n + 2 * sn}")
        kv_k = np.frombuffer(raw[:n], dtype=dtype).reshape(shape)
        kv_v = np.frombuffer(raw[n:2 * n], dtype=dtype).reshape(shape)
        if sn:
            sk = np.frombuffer(
                raw[2 * n:2 * n + sn], dtype=sdtype).reshape(sshape)
            sv = np.frombuffer(raw[2 * n + sn:], dtype=sdtype).reshape(sshape)
        payload = cls(
            request_id=str(meta["request_id"]),
            prompt_tokens=[int(t) for t in meta["prompt_tokens"]],
            first_token=int(meta["first_token"]),
            max_new_tokens=int(meta["max_new_tokens"]),
            temperature=float(meta["temperature"]),
            top_k=int(meta["top_k"]),
            top_p=float(meta["top_p"]),
            stop_token=(None if meta["stop_token"] is None
                        else int(meta["stop_token"])),
            qos=str(meta["qos"]),
            kv_k=kv_k, kv_v=kv_v, kv_scale_k=sk, kv_scale_v=sv)
        payload.validate()
        return payload


def pages_to_wire(kv_k: np.ndarray, kv_v: np.ndarray, *,
                  kv_sk: Optional[np.ndarray] = None,
                  kv_sv: Optional[np.ndarray] = None) -> bytes:
    """Raw page-byte encoding shared with the KV host tier
    (serve/kvtier.py): the same JSON-metadata-line + little-endian raw
    K/V layout ``to_wire`` ships over ``POST /v1/handoff``, minus the
    request identity — a demoted page block is content, not a request.
    ``kv_*`` are any equal-shape arrays (host-tier use: ``[L, pg, KV,
    Dh]`` per page block). int8 pools pass ``kv_sk``/``kv_sv`` — the
    per-token-per-head scale rows ``[L, pg, KV]`` — and get the tagged
    v2 layout ``K + V + scale_K + scale_V``."""
    k = np.ascontiguousarray(kv_k)
    v = np.ascontiguousarray(kv_v)
    meta = {"dtype": str(k.dtype), "shape": list(k.shape)}
    blob = k.tobytes() + v.tobytes()
    if kv_sk is not None:
        sk = np.ascontiguousarray(kv_sk, np.float32)
        sv = np.ascontiguousarray(kv_sv, np.float32)
        meta["cache_dtype"] = "int8"
        meta["scale_dtype"] = str(sk.dtype)
        meta["scale_shape"] = list(sk.shape)
        blob += sk.tobytes() + sv.tobytes()
    return json.dumps(meta).encode() + b"\n" + blob


def pages_from_wire(data: bytes) -> tuple[
        np.ndarray, np.ndarray,
        Optional[np.ndarray], Optional[np.ndarray]]:
    """Decode ``pages_to_wire`` bytes back into (k, v, scale_k, scale_v)
    views — zero-copy ``frombuffer``, so host→device promotion pays one
    upload, not an extra host memcpy. Scales are ``None`` for untagged
    (v1 / full-dtype) blobs."""
    head, sep, raw = data.partition(b"\n")
    if not sep:
        raise ValueError("page wire blob missing metadata line")
    meta = json.loads(head)
    dtype = _np_dtype(meta["dtype"])
    shape = tuple(int(x) for x in meta["shape"])
    n = int(np.prod(shape)) * dtype.itemsize
    sk = sv = None
    sn = 0
    if meta.get("cache_dtype") is not None:
        sdtype = _np_dtype(meta["scale_dtype"])
        sshape = tuple(int(x) for x in meta["scale_shape"])
        sn = int(np.prod(sshape)) * sdtype.itemsize
    if len(raw) != 2 * n + 2 * sn:
        raise ValueError(
            f"page wire blob truncated: {len(raw)} bytes, "
            f"expected {2 * n + 2 * sn}")
    kv_k = np.frombuffer(raw[:n], dtype=dtype).reshape(shape)
    kv_v = np.frombuffer(raw[n:2 * n], dtype=dtype).reshape(shape)
    if sn:
        sk = np.frombuffer(
            raw[2 * n:2 * n + sn], dtype=sdtype).reshape(sshape)
        sv = np.frombuffer(raw[2 * n + sn:], dtype=sdtype).reshape(sshape)
    return kv_k, kv_v, sk, sv


def payload_from_export(req, kv_k: np.ndarray, kv_v: np.ndarray,
                        plen: int,
                        kv_sk: Optional[np.ndarray] = None,
                        kv_sv: Optional[np.ndarray] = None) -> HandoffPayload:
    """Build the payload at flush time: ``kv_*`` are the fetched host
    arrays (dense exports fetch the full cache row — trim to ``plen``),
    and the decode budget is the original budget minus the first token
    the prefill side already emitted. int8 pools pass the fetched scale
    rows too."""
    p = req.params
    payload = HandoffPayload(
        request_id=req.id,
        prompt_tokens=list(req.prompt_tokens),
        first_token=int(req.output_tokens[0]),
        max_new_tokens=int(p.max_new_tokens) - 1,
        temperature=float(p.temperature),
        top_k=int(p.top_k),
        top_p=float(p.top_p),
        stop_token=p.stop_token,
        qos=req.qos,
        kv_k=np.ascontiguousarray(kv_k[:, :plen]),
        kv_v=np.ascontiguousarray(kv_v[:, :plen]),
        kv_scale_k=(None if kv_sk is None
                    else np.ascontiguousarray(kv_sk[:, :plen])),
        kv_scale_v=(None if kv_sv is None
                    else np.ascontiguousarray(kv_sv[:, :plen])))
    payload.validate()
    return payload
