"""Paged-KV handoff between prefill- and decode-specialized engines —
the transfer contract of disaggregated serving (ROADMAP item 2; the
DistServe/Splitwise motif, TPU-native).

Prefill and decode have opposite compute profiles: prefill is
FLOPs-bound (one big causal block over the prompt), decode is
HBM-bandwidth-bound (one token per step against the whole KV). A
unified engine interleaves them on one chip, so a long prefill
head-of-line-blocks every resident decode stream's tokens. Splitting
the fleet into role-specialized pools removes that interference — IF
the prompt's KV can move from the prefill chip to the decode chip. The
page is the natural transfer unit: the prefill side exports the slot's
pages (one batched device→host fetch per admit round), the decode side
adopts them into its OWN ``PageAllocator`` pool (alloc + scatter upload
+ page-table row rebuild, ``owner=`` stamped so
``KFTPU_SANITIZE=refcount`` attributes leaks across the boundary).

Ownership protocol (who owns pages when):

1. **Export** (prefill engine, scheduler thread): the first token is
   sampled, the slot's KV is fetched to host, and the slot is freed —
   but its page references move to a HOLD keyed by request id, not to
   the free list. The payload is now host memory; the pages back it
   until the decode side confirms receipt.
2. **Ack** (prefill model server): the decode replica answered the
   handoff POST — the payload bytes are in its memory — so the hold is
   released (``engine.complete_handoff``). The release is marshalled
   through a queue onto the scheduler thread; the allocator stays
   single-owner.
3. **Failure = recompute**: if the decode side never acks (connect
   failure, 5xx, death mid-POST), ``engine.fail_handoff`` frees the
   hold and the model server re-submits the request LOCALLY as a
   unified request — the prefix cache usually makes the recompute one
   admission. A hold whose request is cancelled or past its deadline is
   reaped by the scheduler like any abandoned request, so a killed
   server can never strand pages (the mid-handoff SIGKILL chaos
   scenario audits exactly this).

Adoption seeds the decode slot at the exact state ``_admit_with_token``
would have left it: ``length = plen`` (the prompt's KV is written; the
first token's is not), ``last_token = first_token``, and the request's
``prompt_tokens`` carry ``prompt + [first_token]`` so recompute
preemption and speculative context reconstruction keep their
invariants. Greedy outputs are therefore token-identical to the unified
path on dense and paged backends (pinned in tests).

Wire format: one JSON metadata line + raw little-endian KV bytes
(dtype/shape in the metadata — bf16 rides as raw ml_dtypes bytes, no
pickle). Rides ``POST /v1/handoff`` with the usual ``X-Kftpu-*``
headers, so a handed-off request keeps ONE trace with a new ``handoff``
phase between ``prefill`` and the decode side's ``queued``/``decode``.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional

import numpy as np


def _np_dtype(name: str) -> np.dtype:
    """Resolve a dtype name, including the ml_dtypes extras (bfloat16)
    numpy itself does not know."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


@dataclasses.dataclass
class HandoffPayload:
    """One prefilled request's transferable state: identity + sampling
    contract + the prompt's KV as contiguous host arrays
    ``[L, plen, KV, Dh]`` (page structure is re-imposed by the adopting
    pool — its page size, its free list, its refcounts)."""

    request_id: str
    prompt_tokens: list[int]        # the plen tokens whose KV rides along
    first_token: int                # sampled on the prefill side (TTFT)
    max_new_tokens: int             # REMAINING decode budget (>= 1)
    temperature: float
    top_k: int
    top_p: float
    stop_token: Optional[int]
    qos: str
    kv_k: np.ndarray
    kv_v: np.ndarray

    @property
    def kv_len(self) -> int:
        return int(self.kv_k.shape[1])

    def validate(self) -> None:
        if self.kv_k.shape != self.kv_v.shape:
            raise ValueError("kv_k/kv_v shape mismatch")
        if self.kv_k.ndim != 4:
            raise ValueError(
                f"KV must be [L, plen, KV, Dh]; got {self.kv_k.shape}")
        if self.kv_len != len(self.prompt_tokens):
            raise ValueError(
                f"KV covers {self.kv_len} positions but the payload "
                f"names {len(self.prompt_tokens)} prompt tokens")
        if self.max_new_tokens < 1:
            raise ValueError("handoff with no decode budget left")

    # -- wire format -------------------------------------------------------

    def to_wire(self) -> bytes:
        """JSON metadata line + raw K bytes + raw V bytes."""
        k = np.ascontiguousarray(self.kv_k)
        v = np.ascontiguousarray(self.kv_v)
        meta = {
            "request_id": self.request_id,
            "prompt_tokens": list(self.prompt_tokens),
            "first_token": int(self.first_token),
            "max_new_tokens": int(self.max_new_tokens),
            "temperature": float(self.temperature),
            "top_k": int(self.top_k),
            "top_p": float(self.top_p),
            "stop_token": self.stop_token,
            "qos": self.qos,
            "dtype": str(k.dtype),
            "shape": list(k.shape),
        }
        return json.dumps(meta).encode() + b"\n" + k.tobytes() + v.tobytes()

    @classmethod
    def from_wire(cls, data: bytes) -> "HandoffPayload":
        head, sep, raw = data.partition(b"\n")
        if not sep:
            raise ValueError("handoff payload missing metadata line")
        meta = json.loads(head)
        dtype = _np_dtype(meta["dtype"])
        shape = tuple(int(x) for x in meta["shape"])
        n = int(np.prod(shape)) * dtype.itemsize
        if len(raw) != 2 * n:
            raise ValueError(
                f"handoff payload truncated: {len(raw)} KV bytes, "
                f"expected {2 * n}")
        kv_k = np.frombuffer(raw[:n], dtype=dtype).reshape(shape)
        kv_v = np.frombuffer(raw[n:], dtype=dtype).reshape(shape)
        payload = cls(
            request_id=str(meta["request_id"]),
            prompt_tokens=[int(t) for t in meta["prompt_tokens"]],
            first_token=int(meta["first_token"]),
            max_new_tokens=int(meta["max_new_tokens"]),
            temperature=float(meta["temperature"]),
            top_k=int(meta["top_k"]),
            top_p=float(meta["top_p"]),
            stop_token=(None if meta["stop_token"] is None
                        else int(meta["stop_token"])),
            qos=str(meta["qos"]),
            kv_k=kv_k, kv_v=kv_v)
        payload.validate()
        return payload


def pages_to_wire(kv_k: np.ndarray, kv_v: np.ndarray) -> bytes:
    """Raw page-byte encoding shared with the KV host tier
    (serve/kvtier.py): the same JSON-metadata-line + little-endian raw
    K/V layout ``to_wire`` ships over ``POST /v1/handoff``, minus the
    request identity — a demoted page block is content, not a request.
    ``kv_*`` are any equal-shape arrays (host-tier use: ``[L, pg, KV,
    Dh]`` per page block)."""
    k = np.ascontiguousarray(kv_k)
    v = np.ascontiguousarray(kv_v)
    meta = {"dtype": str(k.dtype), "shape": list(k.shape)}
    return json.dumps(meta).encode() + b"\n" + k.tobytes() + v.tobytes()


def pages_from_wire(data: bytes) -> tuple[np.ndarray, np.ndarray]:
    """Decode ``pages_to_wire`` bytes back into (k, v) views — zero-copy
    ``frombuffer``, so host→device promotion pays one upload, not an
    extra host memcpy."""
    head, sep, raw = data.partition(b"\n")
    if not sep:
        raise ValueError("page wire blob missing metadata line")
    meta = json.loads(head)
    dtype = _np_dtype(meta["dtype"])
    shape = tuple(int(x) for x in meta["shape"])
    n = int(np.prod(shape)) * dtype.itemsize
    if len(raw) != 2 * n:
        raise ValueError(
            f"page wire blob truncated: {len(raw)} bytes, expected {2 * n}")
    kv_k = np.frombuffer(raw[:n], dtype=dtype).reshape(shape)
    kv_v = np.frombuffer(raw[n:], dtype=dtype).reshape(shape)
    return kv_k, kv_v


def payload_from_export(req, kv_k: np.ndarray, kv_v: np.ndarray,
                        plen: int) -> HandoffPayload:
    """Build the payload at flush time: ``kv_*`` are the fetched host
    arrays (dense exports fetch the full cache row — trim to ``plen``),
    and the decode budget is the original budget minus the first token
    the prefill side already emitted."""
    p = req.params
    payload = HandoffPayload(
        request_id=req.id,
        prompt_tokens=list(req.prompt_tokens),
        first_token=int(req.output_tokens[0]),
        max_new_tokens=int(p.max_new_tokens) - 1,
        temperature=float(p.temperature),
        top_k=int(p.top_k),
        top_p=float(p.top_p),
        stop_token=p.stop_token,
        qos=req.qos,
        kv_k=np.ascontiguousarray(kv_k[:, :plen]),
        kv_v=np.ascontiguousarray(kv_v[:, :plen]))
    payload.validate()
    return payload
