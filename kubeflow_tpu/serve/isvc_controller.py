"""InferenceService controller: reconciles predictor specs into model-server
worker processes behind a routed URL.

Mirrors the reference's ISVC reconciler ((U) kserve
pkg/controller/v1beta1/inferenceservice/controller.go + components/
predictor.go — SURVEY.md §2.3#25), TPU-native shape:

- Replica = a model-server process pinned to chips (no Knative/pods); the
  Worker runtime launches it like any other workload.
- Readiness = /healthz probe; the Router (istio/knative analog) only routes
  to ready replicas, so rollouts and crashes never 502 through the URL.
- Autoscaling = concurrency against ``scale_target`` (the KPA analog),
  scraped from each replica's /metrics; scale-up is eager, scale-down waits
  out a cooldown. min_replicas=0 gives scale-to-zero with cold-start on
  traffic arriving at the router? No — scale-to-zero needs the router to
  queue; v1 clamps at >=1 and records the gap honestly.
- Crash recovery: failed replicas are replaced (fresh Worker object), not
  gang-restarted — serving replicas are independent, unlike SPMD training.
"""

from __future__ import annotations

import json
import time
import urllib.request
from typing import Callable, Optional

from kubeflow_tpu.core.events import EventRecorder, default_recorder
from kubeflow_tpu.core.jobs import (
    RestartPolicy, Worker, WorkerPhase, WorkerSpec, WorkerStatus, WorkloadSpec,
)
from kubeflow_tpu.core.object import ObjectMeta
from kubeflow_tpu.core.serving import InferenceService
from kubeflow_tpu.core.store import (
    AlreadyExistsError, NotFoundError, ObjectStore, WatchEvent,
)
from kubeflow_tpu.operator.controller import ReconcileResult
from kubeflow_tpu.runtime.bootstrap import free_port
from kubeflow_tpu.serve.router import Router

LABEL_ISVC = "serving.tpu.kubeflow.dev/service"
LABEL_REPLICA = "serving.tpu.kubeflow.dev/replica"

_RESYNC = 1.0           # readiness/autoscale poll period (seconds)
_SCALE_DOWN_COOLDOWN = 10.0


def default_probe(url: str, timeout: float = 0.5) -> Optional[dict]:
    """GET /healthz + scrape in-flight from /metrics. None = not ready."""
    try:
        with urllib.request.urlopen(url + "/healthz", timeout=timeout) as r:
            if r.status != 200:
                return None
        out = {"ready": True, "in_flight": 0}
        with urllib.request.urlopen(url + "/metrics", timeout=timeout) as r:
            for line in r.read().decode().splitlines():
                if line.startswith("kftpu_serving_in_flight"):
                    out["in_flight"] = int(float(line.split()[-1]))
        return out
    except OSError:
        return None


class ISVCController:
    kinds = [InferenceService.KIND, Worker.KIND]

    def __init__(self, store: ObjectStore, *,
                 recorder: Optional[EventRecorder] = None,
                 probe: Callable[[str], Optional[dict]] = default_probe):
        self.store = store
        self.recorder = recorder or default_recorder
        self.probe = probe
        self._routers: dict[str, Router] = {}
        self._last_scale: dict[str, float] = {}  # any scale event, per service

    # -- event routing ---------------------------------------------------------

    def key_for(self, ev: WatchEvent) -> Optional[str]:
        obj = ev.object
        if obj.kind == InferenceService.KIND:
            return obj.metadata.key
        if obj.kind == Worker.KIND:
            svc = obj.metadata.labels.get(LABEL_ISVC)
            if svc:
                return f"{obj.metadata.namespace}/{svc}"
        return None

    # -- reconcile -------------------------------------------------------------

    def reconcile(self, key: str) -> Optional[ReconcileResult]:
        namespace, name = key.split("/", 1)
        isvc = self.store.try_get(InferenceService, name, namespace)
        if isvc is None:
            for w in self._workers(key):
                self._delete_worker(w)
            router = self._routers.pop(key, None)
            if router is not None:
                router.stop()
            self._last_scale.pop(key, None)
            return None

        pred = isvc.spec.predictor
        desired = isvc.status.desired_replicas or max(pred.min_replicas, 1)
        desired = max(max(pred.min_replicas, 1), min(desired, pred.max_replicas))

        # Replace crashed/finished replicas; a model server never "succeeds".
        workers = self._workers(key)
        for w in workers:
            if w.status.phase in (WorkerPhase.FAILED, WorkerPhase.SUCCEEDED):
                self.recorder.warning(
                    isvc, "ReplicaCrashed",
                    f"{w.metadata.name}: exit={w.status.exit_code}; replacing")
                self._delete_worker(w)
        workers = [w for w in self._workers(key)]
        by_index = {int(w.metadata.labels[LABEL_REPLICA]): w for w in workers}

        # Converge replica count: create missing, trim highest-index extras.
        for i in range(desired):
            if i not in by_index:
                by_index[i] = self._create_replica(isvc, i)
        for i in sorted(by_index):
            if i >= desired:
                self._delete_worker(by_index.pop(i))

        # Readiness probing → router backends.
        ready_urls = []
        in_flight = 0
        for i, w in sorted(by_index.items()):
            if w.status.phase != WorkerPhase.RUNNING:
                continue
            url = f"http://127.0.0.1:{w.spec.template.config['port']}"
            got = self.probe(url)
            if got is not None:
                ready_urls.append(url)
                in_flight += got.get("in_flight", 0)

        router = self._routers.get(key)
        if router is None:
            router = Router()
            router.start()
            self._routers[key] = router
        router.set_backends({"latest": ready_urls}, {"latest": 100})

        isvc.status.url = router.url
        isvc.status.desired_replicas = desired
        isvc.status.ready_replicas = len(ready_urls)
        isvc.status.traffic = {"latest": 100}
        isvc.status.latest_ready_generation = (
            isvc.metadata.generation if ready_urls else
            isvc.status.latest_ready_generation)
        if ready_urls:
            if not isvc.status.has_condition("Ready"):
                self.recorder.normal(isvc, "Ready",
                                     f"{len(ready_urls)}/{desired} replicas ready "
                                     f"at {router.url}")
            isvc.status.set_condition("PredictorReady")
            isvc.status.set_condition("Ready")
        else:
            isvc.status.set_condition("Ready", status=False,
                                      reason="NoReadyReplicas")

        self._autoscale(isvc, key, in_flight)
        self._update_status(isvc)
        return ReconcileResult(requeue_after=_RESYNC)

    # -- autoscaler (KPA analog) -----------------------------------------------

    def _autoscale(self, isvc: InferenceService, key: str, in_flight: int) -> None:
        pred = isvc.spec.predictor
        ready = isvc.status.ready_replicas
        if ready == 0 or pred.min_replicas >= pred.max_replicas:
            return
        per_replica = in_flight / ready
        desired = isvc.status.desired_replicas
        now = time.monotonic()
        self._last_scale.setdefault(key, now)  # first sight starts the clock
        if per_replica > pred.scale_target and desired < pred.max_replicas:
            isvc.status.desired_replicas = desired + 1
            self._last_scale[key] = now
            self.recorder.normal(
                isvc, "ScaledUp",
                f"concurrency {per_replica:.1f} > target {pred.scale_target}: "
                f"{desired} -> {desired + 1}")
        elif (per_replica < pred.scale_target / 2
              and desired > max(pred.min_replicas, 1)):
            # Scale-down only after a quiet period since ANY scale event —
            # a fresh scale-up must get time to absorb load first.
            if now - self._last_scale[key] >= _SCALE_DOWN_COOLDOWN:
                isvc.status.desired_replicas = desired - 1
                self._last_scale[key] = now
                self.recorder.normal(
                    isvc, "ScaledDown",
                    f"concurrency {per_replica:.1f} < half target: "
                    f"{desired} -> {desired - 1}")

    # -- children --------------------------------------------------------------

    def _workers(self, key: str) -> list[Worker]:
        namespace, name = key.split("/", 1)
        return self.store.list(Worker, namespace=namespace,
                               label_selector={LABEL_ISVC: name})

    def _create_replica(self, isvc: InferenceService, index: int) -> Worker:
        pred = isvc.spec.predictor
        model = pred.model
        port = free_port()
        config = {
            "service": model.model_name or isvc.metadata.name,
            "model": model.config or {"preset": "tiny"},
            "storage_uri": model.storage_uri,
            "batching": pred.batching.model_dump(),
            "port": port,
        }
        if isvc.spec.transformer is not None:
            config["transformer"] = isvc.spec.transformer.model_dump()
        w = Worker(
            metadata=ObjectMeta(
                name=f"{isvc.metadata.name}-predictor-{index}",
                namespace=isvc.metadata.namespace,
                labels={LABEL_ISVC: isvc.metadata.name,
                        LABEL_REPLICA: str(index)},
                owner=isvc.key,
            ),
            spec=WorkerSpec(
                job=isvc.metadata.key,
                replica_index=index,
                num_workers=1,
                template=WorkloadSpec(entrypoint="model_server", config=config),
                resources=pred.resources,
                restart_policy=RestartPolicy.ON_FAILURE,
            ),
            status=WorkerStatus(),
        )
        try:
            created = self.store.create(w)
        except AlreadyExistsError:
            return self.store.get(Worker, w.metadata.name, w.metadata.namespace)
        self.recorder.normal(isvc, "CreatedReplica",
                             f"{w.metadata.name} on port {port}")
        return created

    def _delete_worker(self, w: Worker) -> None:
        try:
            self.store.delete(Worker, w.metadata.name, w.metadata.namespace)
        except NotFoundError:
            pass

    def _update_status(self, isvc: InferenceService) -> None:
        try:
            self.store.update_status(isvc)
        except NotFoundError:
            pass

    def shutdown(self) -> None:
        for router in self._routers.values():
            router.stop()
        self._routers.clear()
