"""InferenceService controller: reconciles predictor specs into model-server
worker processes behind a routed URL.

Mirrors the reference's ISVC reconciler ((U) kserve
pkg/controller/v1beta1/inferenceservice/controller.go + components/
predictor.go — SURVEY.md §2.3#25), TPU-native shape:

- Replica = a model-server process pinned to chips (no Knative/pods); the
  Worker runtime launches it like any other workload.
- Readiness = /healthz probe; the Router (istio/knative analog) only routes
  to ready replicas, so rollouts and crashes never 502 through the URL.
- Autoscaling = concurrency against ``scale_target`` (the KPA analog),
  scraped from each replica's /metrics; scale-up is eager, scale-down waits
  out a cooldown. **min_replicas=0 scales to zero**: the router parks
  requests (activator analog), the parked-request gauge is the 0→1
  activation signal, and an idle service drops its last replica after the
  cooldown — the Knative serverless path ((U) kserve serverless mode via
  Knative PodAutoscaler + activator).
- Canary = generation-based traffic split ((U) kserve canaryTrafficPercent
  on the predictor): a spec update with ``canary_traffic_percent=p`` keeps
  the previous generation's replicas serving ``100-p``% while the new
  generation takes ``p``%; clearing the percent (or setting 100) promotes —
  old-generation replicas are torn down once the new generation is ready.
- Crash recovery: failed replicas are replaced (fresh Worker object), not
  gang-restarted — serving replicas are independent, unlike SPMD training.
- Graceful drain ((U) pod terminationGracePeriod + Envoy connection drain):
  scale-down and rollout retirement first remove a replica from the router
  rotation (no new traffic), then wait for its in-flight requests to finish
  — up to ``PredictorSpec.drain_deadline_s`` — before deleting the worker.
  Crashed or never-started replicas skip the drain and delete immediately.
"""

from __future__ import annotations

import time
import urllib.request
from typing import Callable, Optional

from kubeflow_tpu.core.events import EventRecorder, default_recorder
from kubeflow_tpu.core.jobs import (
    RestartPolicy, Worker, WorkerPhase, WorkerSpec, WorkerStatus, WorkloadSpec,
)
from kubeflow_tpu.core.object import ObjectMeta
from kubeflow_tpu.core.serving import InferenceService, SLOPolicy
from kubeflow_tpu.obs.registry import contract_note_series, parse_exposition
from kubeflow_tpu.core.store import (
    AlreadyExistsError, NotFoundError, ObjectStore, WatchEvent,
)
from kubeflow_tpu.obs.trace import get_tracer
from kubeflow_tpu.operator.controller import ReconcileResult
from kubeflow_tpu.runtime.bootstrap import free_port
from kubeflow_tpu.serve.router import Router

LABEL_ISVC = "serving.tpu.kubeflow.dev/service"
LABEL_REPLICA = "serving.tpu.kubeflow.dev/replica"
LABEL_GEN = "serving.tpu.kubeflow.dev/generation"
LABEL_ROLE = "serving.tpu.kubeflow.dev/role"

_RESYNC = 1.0           # readiness/autoscale poll period (seconds)
_SCALE_DOWN_COOLDOWN = 10.0
_SCALE_TO_ZERO_COOLDOWN = 10.0

#: Every series name ``default_probe`` matches on — the autoscaler's half
#: of the engine↔controller metrics contract. The match chain below uses
#: the same literals; ``kftpu lint``'s X701 checks each against the
#: engine's definition sites, and tests/test_contracts.py pins the pair
#: against a REAL rendered /metrics payload (a rename on either side
#: fails both).
_PROBE_SERIES = (
    "kftpu_serving_in_flight",
    "kftpu_serving_requests_total",
    "kftpu_serving_ttft_p95_ms",
    "kftpu_serving_queue_delay_p95_ms",
    "kftpu_serving_qos_ttft_p95_ms",
    "kftpu_serving_qos_queue_delay_p95_ms",
    "kftpu_engine_kv_tier_pressure",
)


def signals_from_samples(samples) -> dict:
    """Fold one parsed ``/metrics`` sample set into the probe's signal
    dict — the ONE autoscaling-signal fold, shared by ``default_probe``
    (live scrape) and ``obs.fleet.HistoryProbe`` (history-backed), so
    the two signal sources can never produce different autoscaler
    decisions from the same exposition. Empty ``samples`` returns the
    ready-but-blind defaults (the unparseable-exposition shape)."""
    out = {"ready": True, "in_flight": 0, "requests_total": 0,
           "ttft_p95_ms": None, "queue_delay_p95_ms": None,
           "qos_ttft_p95_ms": {}, "qos_queue_delay_p95_ms": {},
           "kv_tier_pressure": 0.0}
    for name, labels, value in samples:
        if name in _PROBE_SERIES:
            # Contract audit: this scrape CONSUMED the series (no-op
            # unless KFTPU_SANITIZE=contract).
            contract_note_series(name, "consumed")
        if name == "kftpu_serving_in_flight":
            out["in_flight"] = int(value)
        elif name == "kftpu_serving_requests_total":
            out["requests_total"] += int(value)
        elif name == "kftpu_serving_ttft_p95_ms":
            out["ttft_p95_ms"] = max(out["ttft_p95_ms"] or 0.0, value)
        elif name == "kftpu_serving_queue_delay_p95_ms":
            out["queue_delay_p95_ms"] = max(
                out["queue_delay_p95_ms"] or 0.0, value)
        elif name == "kftpu_engine_kv_tier_pressure":
            # The engine's own demote-urgency ratio (pressure_fn
            # fold: pool occupancy x queue delay x adapter waits,
            # now including remote-tier churn) — the split-pool
            # autoscaler folds it into the decode plan.
            out["kv_tier_pressure"] = max(
                out["kv_tier_pressure"], value)
        elif name in ("kftpu_serving_qos_ttft_p95_ms",
                      "kftpu_serving_qos_queue_delay_p95_ms"):
            cls = labels.get("qos")
            if cls:
                key = ("qos_ttft_p95_ms" if name.endswith("ttft_p95_ms")
                       else "qos_queue_delay_p95_ms")
                out[key][cls] = max(out[key].get(cls, 0.0), value)
    return out


def default_probe(url: str, timeout: float = 0.5) -> Optional[dict]:
    """GET /healthz + scrape autoscaling signals from /metrics. None = not
    ready. Beyond the concurrency gauges, the probe carries the engine's
    own latency signals — aggregate and per-QoS-class TTFT/queue-delay
    p95s — which the SLO autoscaler weighs against ``SLOPolicy`` targets.
    Signal keys are None/empty when the replica has no traffic history
    yet: the autoscaler reads "no signal + no load" as idle and "no
    signal + load" as blindness (hold, don't flap)."""
    try:
        with urllib.request.urlopen(url + "/healthz", timeout=timeout) as r:
            if r.status != 200:
                return None
        with urllib.request.urlopen(url + "/metrics", timeout=timeout) as r:
            text = r.read().decode()
        try:
            samples = parse_exposition(text)
        except ValueError:
            # Unparseable exposition: ready, but blind.
            return signals_from_samples(())
        return signals_from_samples(samples)
    except OSError:
        return None


class ISVCController:
    kinds = [InferenceService.KIND, Worker.KIND]

    def __init__(self, store: ObjectStore, *,
                 recorder: Optional[EventRecorder] = None,
                 probe: Callable[[str], Optional[dict]] = default_probe):
        self.store = store
        self.recorder = recorder or default_recorder
        self.probe = probe
        self._routers: dict[str, Router] = {}
        self._last_scale: dict[str, float] = {}  # any scale event, per service
        # Last observed request *traffic* per service — the KPA counts
        # idleness from here, not from scale events ((U) Knative KPA
        # stable-window semantics). Fed by three signals: in-flight/parked
        # gauges, the router's per-request completion stamp, and the
        # replicas' served-request counters (catches sub-resync requests
        # sent straight to a replica, bypassing the router). Counters are
        # tracked PER REPLICA: only a same-replica increase is activity —
        # a summed counter dips when one replica's probe flakes and then
        # "recovers", which would read as fresh traffic and grant the
        # service another cooldown of life on every flake.
        self._last_active: dict[str, float] = {}
        self._req_totals: dict[str, dict[str, int]] = {}
        # Graceful drain state: service key -> {worker name -> hard drain
        # deadline (monotonic)}. A draining replica took its last routed
        # request the pass it entered here; it is deleted once idle or at
        # the deadline, whichever comes first.
        self._draining: dict[str, dict[str, float]] = {}

    # -- event routing ---------------------------------------------------------

    def key_for(self, ev: WatchEvent) -> Optional[str]:
        obj = ev.object
        if obj.kind == InferenceService.KIND:
            return obj.metadata.key
        if obj.kind == Worker.KIND:
            svc = obj.metadata.labels.get(LABEL_ISVC)
            if svc:
                return f"{obj.metadata.namespace}/{svc}"
        return None

    # -- reconcile -------------------------------------------------------------

    def reconcile(self, key: str) -> Optional[ReconcileResult]:
        namespace, name = key.split("/", 1)
        isvc = self.store.try_get(InferenceService, name, namespace)
        if isvc is None:
            for w in self._workers(key):
                self._delete_worker(w)
            router = self._routers.pop(key, None)
            if router is not None:
                router.stop()
            self._last_scale.pop(key, None)
            self._last_active.pop(key, None)
            self._req_totals.pop(key, None)
            self._draining.pop(key, None)
            return None

        pred = isvc.spec.predictor
        router = self._routers.get(key)
        if router is None:
            router = Router()
            router.start()
            self._routers[key] = router

        if pred.pools is not None:
            # Disaggregated prefill/decode pools: a dedicated converge
            # path (no canary/scale-to-zero interplay — the pool split
            # IS the traffic topology).
            return self._reconcile_pools(isvc, key, router)

        # Desired count: autoscaler-owned once seeded; 0 is a real state.
        desired = isvc.status.desired_replicas
        if desired is None:
            desired = max(pred.min_replicas, 1)
        desired = max(pred.min_replicas, min(desired, pred.max_replicas))
        pending = router.pending
        if desired == 0 and pending > 0:
            # Activation: a request is parked at the router — 0→1 cold start.
            desired = 1
            self._last_scale[key] = time.monotonic()
            self.recorder.normal(
                isvc, "ColdStart",
                f"{pending} request(s) queued at the router: 0 -> 1")

        # Replace crashed/finished replicas; a model server never "succeeds".
        for w in self._workers(key):
            if w.status.phase in (WorkerPhase.FAILED, WorkerPhase.SUCCEEDED):
                self.recorder.warning(
                    isvc, "ReplicaCrashed",
                    f"{w.metadata.name}: exit={w.status.exit_code}; replacing")
                self._delete_worker(w)

        gen = isvc.metadata.generation
        by: dict[tuple[int, int], Worker] = {}
        for w in self._workers(key):
            g = int(w.metadata.labels.get(LABEL_GEN, gen))
            i = int(w.metadata.labels[LABEL_REPLICA])
            by[(g, i)] = w
        prev_gens = sorted({g for g, _ in by if g != gen})
        canary_p = pred.canary_traffic_percent
        # desired == 0 suspends the canary: a scaled-to-zero service keeps
        # NO generation running (otherwise the previous generation's
        # replicas would be unreachable by the cleanup below and idle on).
        canary_active = (canary_p is not None and canary_p < 100
                         and bool(prev_gens) and desired > 0)
        if canary_active:
            # The previous generation keeps serving at full strength; the
            # canary generation gets a traffic-proportional slice (>=1).
            # Both groups are CONVERGED every pass — crashed previous-
            # generation replicas are recreated and autoscaler resizes apply
            # to both, so a long-lived canary never bleeds stable capacity
            # while its group still claims 100-p percent of traffic.
            n_latest = min(max(1, round(desired * canary_p / 100)), desired)
            n_prev = desired
        else:
            n_latest = desired
            n_prev = 0

        # Converge the latest generation: create missing, trim extras.
        for i in range(n_latest):
            if (gen, i) not in by:
                by[(gen, i)] = self._create_replica(isvc, i, gen)
        for (g, i) in sorted(by):
            if g == gen and i >= n_latest:
                self._retire_worker(key, router, by.pop((g, i)), isvc)
        pg = prev_gens[-1] if prev_gens else None
        if canary_active:
            # Converge the newest previous generation to its share. A
            # recreated replica MUST run the previous generation's config —
            # the isvc spec already holds the canary's — so it is cloned
            # from a surviving same-generation sibling. Today prev_gens is
            # derived from live workers in ``by`` so a sibling exists, but
            # that is an invariant of this pass's bookkeeping, not of the
            # store — guard it so a refactor (or a concurrent delete racing
            # the worker list) degrades to "skip convergence this pass"
            # instead of killing the reconcile loop with StopIteration.
            sibling = next(
                (w for (g, _), w in sorted(by.items()) if g == pg), None)
            if sibling is None:
                self.recorder.warning(
                    isvc, "CanaryNoSibling",
                    f"previous generation {pg} has no surviving replica to "
                    "clone; skipping its convergence this pass")
            else:
                for i in range(n_prev):
                    if (pg, i) not in by:
                        by[(pg, i)] = self._create_replica(
                            isvc, i, pg, clone_from=sibling)
                for (g, i) in sorted(by):
                    if g == pg and i >= n_prev:
                        self._retire_worker(key, router, by.pop((g, i)),
                                            isvc)

        # Readiness probing, per generation. ``signals`` collects each
        # probed replica's latency scrape for the SLO autoscaler;
        # ``probes_failed`` counts RUNNING replicas that did not answer —
        # the "missing/stale signal" condition that HOLDS scaling.
        ready_by_gen: dict[int, list[str]] = {}
        in_flight = 0
        req_counts: dict[str, int] = {}      # replica name -> counter seen
        signals: list[dict] = []
        probes_failed = 0
        for (g, i), w in sorted(by.items()):
            if w.status.phase != WorkerPhase.RUNNING:
                continue
            url = f"http://127.0.0.1:{w.spec.template.config['port']}"
            got = self.probe(url)
            if got is not None:
                ready_by_gen.setdefault(g, []).append(url)
                in_flight += got.get("in_flight", 0)
                req_counts[w.metadata.name] = got.get("requests_total", 0)
                signals.append(got)
            else:
                probes_failed += 1

        # Activity clock: any traffic signal resets idleness. A replica's
        # counter counts as activity only against ITS OWN last reading
        # (restart resets read as no activity; a flaked probe keeps the
        # old reading rather than zeroing the baseline).
        now = time.monotonic()
        prev_counts = self._req_totals.get(key, {})
        if in_flight > 0 or pending > 0:
            self._last_active[key] = now
        if any(n in prev_counts and c > prev_counts[n]
               for n, c in req_counts.items()):
            self._last_active[key] = now
        live = {w.metadata.name for w in by.values()}
        self._req_totals[key] = {
            n: c for n, c in {**prev_counts, **req_counts}.items()
            if n in live}
        self._last_active[key] = max(self._last_active.get(key, 0.0),
                                     router.last_activity)

        latest_ready = ready_by_gen.get(gen, [])
        if canary_active and ready_by_gen.get(pg):
            # Retire generations older than the newest previous one only
            # once that group is actually serving — mirroring the rolling
            # path's no-outage handover (they still back the 100-p share
            # until then via prev_urls below).
            for (g, i) in sorted(by):
                if g != gen and g != pg:
                    self._retire_worker(key, router, by.pop((g, i)), isvc)
                    ready_by_gen.pop(g, None)
        if not canary_active:
            # Rolling update: drop old generations once the new one is ready
            # (or immediately when scaling to zero — nothing to hand over to).
            if latest_ready or n_latest == 0:
                for (g, i) in sorted(by):
                    if g != gen:
                        self._retire_worker(key, router, by.pop((g, i)),
                                            isvc)
                        ready_by_gen.pop(g, None)

        # Router backends + traffic split.
        if canary_active:
            prev_urls = [u for g in prev_gens
                         for u in ready_by_gen.get(g, [])]
            router.set_backends(
                {"latest": latest_ready, "previous": prev_urls},
                {"latest": canary_p, "previous": 100 - canary_p})
            traffic = {"latest": canary_p, "previous": 100 - canary_p}
        else:
            # Rolling update: until the new generation is ready, the old one
            # keeps taking traffic (no outage window).
            urls = latest_ready or [
                u for us in ready_by_gen.values() for u in us]
            router.set_backends({"latest": urls}, {"latest": 100})
            traffic = {"latest": 100}

        ready_urls = [u for urls in ready_by_gen.values() for u in urls]
        isvc.status.url = router.url
        isvc.status.desired_replicas = desired
        isvc.status.ready_replicas = len(ready_urls)
        isvc.status.traffic = traffic
        sp = get_tracer().current()
        if sp is not None:
            # Annotate the Controller-owned reconcile span: what this pass
            # converged to (the numbers a slow-reconcile trace needs to be
            # diagnosable without re-running it).
            sp.set_attrs(desired=desired, ready=len(ready_urls),
                         pending=pending, canary=bool(canary_active))
        if latest_ready:
            isvc.status.latest_ready_generation = gen
        if ready_urls:
            if not isvc.status.has_condition("Ready"):
                self.recorder.normal(isvc, "Ready",
                                     f"{len(ready_urls)}/{desired} replicas ready "
                                     f"at {router.url}")
            isvc.status.set_condition("PredictorReady")
            isvc.status.set_condition("Ready")
        elif desired == 0:
            isvc.status.set_condition("Ready", status=False,
                                      reason="ScaledToZero")
        else:
            isvc.status.set_condition("Ready", status=False,
                                      reason="NoReadyReplicas")

        self._autoscale(isvc, key, in_flight, pending,
                        signals=signals, probes_failed=probes_failed)
        self._update_status(isvc)
        return ReconcileResult(requeue_after=_RESYNC)

    # -- disaggregated pools (ISSUE 12 tentpole) -------------------------------

    def _reconcile_pools(self, isvc: InferenceService, key: str,
                         router: Router) -> ReconcileResult:
        """Converge a ``{prefill: N, decode: M}`` predictor: two
        role-specialized worker pools behind the token-aware router.
        Each replica gets its pool's engine role stamped into its
        batching config; ready members register per-role via
        ``router.set_pools`` (which also runs the placement-signal
        scrape), and the split autoscaler resizes each pool on its own
        signal."""
        pred = isvc.spec.predictor
        pools = pred.pools
        desired = dict(isvc.status.desired_pool_replicas)
        for role in ("prefill", "decode"):
            base = getattr(pools, role)
            want = desired.get(role, base)
            desired[role] = max(base, min(want, pools.cap(role)))

        # Replace crashed replicas (a model server never "succeeds").
        for w in self._workers(key):
            if w.status.phase in (WorkerPhase.FAILED, WorkerPhase.SUCCEEDED):
                self.recorder.warning(
                    isvc, "ReplicaCrashed",
                    f"{w.metadata.name}: exit={w.status.exit_code}; "
                    "replacing")
                self._delete_worker(w)

        gen = isvc.metadata.generation
        by: dict[tuple[str, int], Worker] = {}
        for w in self._workers(key):
            role = w.metadata.labels.get(LABEL_ROLE, "prefill")
            i = int(w.metadata.labels[LABEL_REPLICA])
            by[(role, i)] = w
        for role in ("prefill", "decode"):
            for i in range(desired[role]):
                if (role, i) not in by:
                    by[(role, i)] = self._create_replica(isvc, i, gen,
                                                         role=role)
        for (role, i) in sorted(by):
            if role in desired and i >= desired[role]:
                self._retire_worker(key, router, by.pop((role, i)), isvc)

        # Probe per pool: readiness + the SLO signals each pool scales
        # on (prefill: queue-delay p95 — the admission backlog lives
        # there; decode: TTFT p95 of adopted requests — the decode-side
        # scheduling latency).
        ready: dict[str, list[str]] = {"prefill": [], "decode": []}
        signals: dict[str, list[dict]] = {"prefill": [], "decode": []}
        probes_failed = 0
        in_flight = 0
        for (role, i), w in sorted(by.items()):
            if w.status.phase != WorkerPhase.RUNNING:
                continue
            url = self._replica_url(w)
            got = self.probe(url)
            if got is not None:
                ready.setdefault(role, []).append(url)
                signals.setdefault(role, []).append(got)
                in_flight += got.get("in_flight", 0)
            else:
                probes_failed += 1

        router.set_pools({"prefill": ready["prefill"],
                          "decode": ready["decode"]})

        n_ready = sum(len(u) for u in ready.values())
        n_desired = sum(desired.values())
        isvc.status.url = router.url
        isvc.status.desired_replicas = n_desired
        isvc.status.desired_pool_replicas = desired
        isvc.status.ready_replicas = n_ready
        isvc.status.traffic = {"latest": 100}
        sp = get_tracer().current()
        if sp is not None:
            sp.set_attrs(desired=n_desired, ready=n_ready, pooled=True)
        if ready["prefill"] and ready["decode"]:
            if not isvc.status.has_condition("Ready"):
                self.recorder.normal(
                    isvc, "Ready",
                    f"pools ready (prefill {len(ready['prefill'])}/"
                    f"{desired['prefill']}, decode {len(ready['decode'])}/"
                    f"{desired['decode']}) at {router.url}")
            isvc.status.set_condition("PredictorReady")
            isvc.status.set_condition("Ready")
        else:
            isvc.status.set_condition(
                "Ready", status=False,
                reason=("NoReadyReplicas" if n_ready == 0
                        else "PoolDegraded"))

        if pred.slo is not None:
            self._autoscale_pools(isvc, key, signals, probes_failed,
                                  desired)
        self._update_status(isvc)
        return ReconcileResult(requeue_after=_RESYNC)

    def _autoscale_pools(self, isvc: InferenceService, key: str,
                         signals: dict[str, list[dict]],
                         probes_failed: int,
                         desired: dict[str, int]) -> None:
        """Split-pool SLO autoscaling: each pool forms its OWN ratio —
        prefill against ``target_queue_delay_ms``, decode against
        ``target_ttft_ms`` — and resizes independently within its spec
        bounds, sharing the hysteresis band and cooldown. Blind pools
        (failed probes, fewer reporters than members) HOLD, exactly
        like the homogeneous autoscaler."""
        pred = isvc.spec.predictor
        slo = pred.slo
        pools = pred.pools
        now = time.monotonic()
        self._last_scale.setdefault(key, now)
        if probes_failed:
            return
        if now - self._last_scale[key] < slo.cooldown_s:
            return
        plans = (
            ("prefill", "queue_delay_p95_ms", slo.target_queue_delay_ms),
            ("decode", "ttft_p95_ms", slo.target_ttft_ms),
        )
        for role, sig_key, target in plans:
            if target is None:
                continue
            sigs = signals.get(role, [])
            if len(sigs) < desired.get(role, 0):
                continue            # pool not fully reporting: hold
            vals = [s.get(sig_key) for s in sigs]
            loaded = any(s.get("in_flight", 0) > 0 for s in sigs)
            if any(v is None for v in vals):
                if loaded:
                    continue        # loaded but blind: hold
                vals = [v for v in vals if v is not None]
            if not vals:
                continue
            ratio = max(vals) / target
            if role == "decode":
                # Third-tier fold (ISSUE 17): a decode pool churning KV
                # through the remote store is capacity-starved even when
                # its TTFT still meets target — the engine's pressure_fn
                # ratio (>= 1.0 = urgent) rides the probe, and the WORSE
                # of the two signals drives the plan. Symmetric on the
                # way down: high tier pressure blocks a scale-down that
                # the latency signal alone would have taken.
                pressure = max(
                    (s.get("kv_tier_pressure") or 0.0) for s in sigs)
                if pressure > ratio:
                    ratio, sig_key = pressure, "kv_tier_pressure"
            cur = desired[role]
            if ratio > slo.scale_up_ratio and cur < pools.cap(role):
                desired[role] = cur + 1
                self._last_scale[key] = now
                self.recorder.normal(
                    isvc, "ScaledUp",
                    f"{role} pool {sig_key} ratio {ratio:.2f} > "
                    f"{slo.scale_up_ratio}: {cur} -> {cur + 1}")
            elif ratio < slo.scale_down_ratio \
                    and cur > getattr(pools, role):
                desired[role] = cur - 1
                self._last_scale[key] = now
                self.recorder.normal(
                    isvc, "ScaledDown",
                    f"{role} pool {sig_key} ratio {ratio:.2f} < "
                    f"{slo.scale_down_ratio}: {cur} -> {cur - 1}")
        isvc.status.desired_pool_replicas = desired

    # -- autoscaler (KPA analog) -----------------------------------------------

    def _autoscale(self, isvc: InferenceService, key: str, in_flight: int,
                   pending: int, signals: Optional[list[dict]] = None,
                   probes_failed: int = 0) -> None:
        pred = isvc.spec.predictor
        if pred.slo is not None:
            return self._autoscale_slo(isvc, key, in_flight, pending,
                                       list(signals or ()), probes_failed)
        ready = isvc.status.ready_replicas
        desired = isvc.status.desired_replicas
        if ready == 0:
            return
        if pred.min_replicas >= pred.max_replicas and pred.min_replicas > 0:
            return   # fixed-size service; min=0,max=1 still autoscales 0↔1
        per_replica = in_flight / ready
        now = time.monotonic()
        self._last_scale.setdefault(key, now)  # first sight starts the clock
        if per_replica > pred.scale_target and desired < pred.max_replicas:
            isvc.status.desired_replicas = desired + 1
            self._last_scale[key] = now
            self.recorder.normal(
                isvc, "ScaledUp",
                f"concurrency {per_replica:.1f} > target {pred.scale_target}: "
                f"{desired} -> {desired + 1}")
        elif (per_replica < pred.scale_target / 2
              and desired > pred.min_replicas):
            # Scale-down only after a quiet period since ANY scale event —
            # a fresh scale-up must get time to absorb load first. Dropping
            # the LAST replica (scale-to-zero) additionally requires a fully
            # idle service: nothing in flight, nothing parked at the router.
            to_zero = desired == 1
            if to_zero and (in_flight > 0 or pending > 0):
                return
            cooldown = (_SCALE_TO_ZERO_COOLDOWN if to_zero
                        else _SCALE_DOWN_COOLDOWN)
            # Scale-to-zero counts idleness from the LATER of the last
            # scale event and the last observed request activity ((U)
            # Knative KPA: the stable window for the 1→0 decision is over
            # *traffic*). Clocking from scale events alone culled
            # cold-started replicas the instant they answered a parked
            # request whenever the cold start outlasted the cooldown
            # (spawn + init + compile burned the whole quiet period).
            # N→N-1 consolidation stays concurrency-driven: low average
            # concurrency downsizes even while trickle traffic flows —
            # gating it on traffic silence would pin over-provisioned
            # replicas forever.
            idle_since = self._last_scale[key]
            if to_zero:
                idle_since = max(idle_since,
                                 self._last_active.get(key, 0.0))
            if now - idle_since >= cooldown:
                isvc.status.desired_replicas = desired - 1
                self._last_scale[key] = now
                self.recorder.normal(
                    isvc, "ScaledToZero" if to_zero else "ScaledDown",
                    f"concurrency {per_replica:.1f} < half target: "
                    f"{desired} -> {desired - 1}")

    # -- SLO-driven autoscaler (the closed loop: ISSUE 6 tentpole) -------------

    def _autoscale_slo(self, isvc: InferenceService, key: str,
                       in_flight: int, pending: int, signals: list[dict],
                       probes_failed: int) -> None:
        """Signal-driven replica sizing: the KPA loop re-pointed at the
        engine's OWN latency signals. Each ready replica's queue-delay/
        TTFT p95s (per-class-weighted when exposed) form a utilization
        ratio against the ``SLOPolicy`` targets; the pool mean scales the
        service up past ``scale_up_ratio``, down below
        ``scale_down_ratio``, and HOLDS inside the hysteresis band, after
        any failed probe (blind — don't flap), and within ``cooldown_s``
        of the previous resize. Scale-down goes through the normal retire
        path, so a draining replica always finishes its in-flight work
        before teardown; 1→0 additionally requires a fully idle service
        (the scale-to-zero traffic-silence rule)."""
        pred = isvc.spec.predictor
        slo = pred.slo
        ready = isvc.status.ready_replicas
        desired = isvc.status.desired_replicas
        if ready == 0 or not desired:
            return     # 0→1 activation is reconcile's parked-request path
        now = time.monotonic()
        self._last_scale.setdefault(key, now)  # first sight starts the clock
        if probes_failed or len(signals) < desired:
            # Missing/stale signals: a RUNNING replica did not answer its
            # scrape (wedged, or SIGKILLed between scrape and resize), or
            # fewer replicas report than the service is supposed to have
            # (a crash replacement or scale-up still starting). Resizing
            # on partial vision is how autoscalers flap — hold until the
            # fleet is whole and every member reports.
            return
        ratios = [self._slo_ratio(slo, s) for s in signals]
        if not ratios or any(r is None for r in ratios):
            return     # a loaded replica exposes no latency signal: hold
        ratio = sum(ratios) / len(ratios)
        if now - self._last_scale[key] < slo.cooldown_s:
            return     # cooldown: no back-to-back resizes (flap guard)
        if ratio > slo.scale_up_ratio and desired < pred.max_replicas:
            isvc.status.desired_replicas = desired + 1
            self._last_scale[key] = now
            self.recorder.normal(
                isvc, "ScaledUp",
                f"SLO ratio {ratio:.2f} > {slo.scale_up_ratio}: "
                f"{desired} -> {desired + 1}")
        elif ratio < slo.scale_down_ratio and desired > pred.min_replicas:
            to_zero = desired == 1
            if to_zero:
                # Dropping the LAST replica needs a fully idle service
                # and traffic silence, same as the concurrency path.
                if in_flight > 0 or pending > 0:
                    return
                idle_since = max(self._last_scale[key],
                                 self._last_active.get(key, 0.0))
                if now - idle_since < _SCALE_TO_ZERO_COOLDOWN:
                    return
            isvc.status.desired_replicas = desired - 1
            self._last_scale[key] = now
            self.recorder.normal(
                isvc, "ScaledToZero" if to_zero else "ScaledDown",
                f"SLO ratio {ratio:.2f} < {slo.scale_down_ratio}: "
                f"{desired} -> {desired - 1}")

    @staticmethod
    def _slo_ratio(slo: SLOPolicy, sig: dict) -> Optional[float]:
        """One replica's utilization against the SLO targets (1.0 = at
        target). Per-class p95s are weighted by ``slo.class_weights``
        when the replica exposes them (interactive misses dominate the
        decision; batch backlog barely registers); otherwise the
        aggregate p95s apply, taking the worse of the TTFT and
        queue-delay ratios. None = the replica carries traffic but
        exposes no latency signal — blind, so the caller holds."""
        def _ratios(ttft_ms, qd_ms):
            rs = []
            if slo.target_ttft_ms is not None and ttft_ms is not None:
                rs.append(ttft_ms / slo.target_ttft_ms)
            if slo.target_queue_delay_ms is not None and qd_ms is not None:
                rs.append(qd_ms / slo.target_queue_delay_ms)
            return rs

        qos_t = sig.get("qos_ttft_p95_ms") or {}
        qos_q = sig.get("qos_queue_delay_p95_ms") or {}
        num = den = 0.0
        for cls in set(qos_t) | set(qos_q):
            w = slo.class_weights.get(cls, 0.0)
            rs = _ratios(qos_t.get(cls), qos_q.get(cls))
            if w > 0 and rs:
                num += w * max(rs)
                den += w
        if den > 0:
            return num / den
        rs = _ratios(sig.get("ttft_p95_ms"), sig.get("queue_delay_p95_ms"))
        if rs:
            return max(rs)
        # No latency signal at all: an idle replica reads as ratio 0
        # (scale-down-eligible); a loaded one is blind — hold.
        return None if sig.get("in_flight", 0) > 0 else 0.0

    # -- children --------------------------------------------------------------

    def _workers(self, key: str) -> list[Worker]:
        namespace, name = key.split("/", 1)
        return self.store.list(Worker, namespace=namespace,
                               label_selector={LABEL_ISVC: name})

    @staticmethod
    def _replica_url(w: Worker) -> str:
        return f"http://127.0.0.1:{w.spec.template.config['port']}"

    def _retire_worker(self, key: str, router: Router, w: Worker,
                       isvc: Optional[InferenceService] = None) -> None:
        """Graceful drain ((U) pod terminationGracePeriod + Envoy drain):
        a RUNNING replica being scaled away stops receiving traffic this
        same pass (its url leaves the router rotation AND is marked
        draining), finishes its in-flight requests, and is deleted once
        idle — or at the per-service drain deadline. Non-running replicas
        (crashed, never started) delete immediately. Callers invoke this
        every reconcile pass; the per-worker state machine converges."""
        name = w.metadata.name
        url = self._replica_url(w)
        st = self._draining.setdefault(key, {})
        if w.status.phase != WorkerPhase.RUNNING:
            st.pop(name, None)
            router.set_draining(url, False)
            self._delete_worker(w)
            return
        now = time.monotonic()
        if name not in st:
            grace = 30.0
            if isvc is not None:
                grace = isvc.spec.predictor.drain_deadline_s
            st[name] = now + max(0.0, grace)
            router.set_draining(url, True)
            if isvc is not None:
                self.recorder.normal(
                    isvc, "Draining",
                    f"{name}: finishing in-flight requests "
                    f"(hard deadline {grace:.0f}s)")
        got = self.probe(url)
        if got is None or got.get("in_flight", 0) <= 0 or now >= st[name]:
            st.pop(name, None)
            router.set_draining(url, False)
            self._delete_worker(w)

    def _create_replica(self, isvc: InferenceService, index: int,
                        generation: int,
                        clone_from: Optional[Worker] = None,
                        role: Optional[str] = None) -> Worker:
        pred = isvc.spec.predictor
        port = free_port()
        resources = pred.resources
        parallelism: dict[str, int] = {}
        if pred.parallelism.total > 1:
            # Tensor-parallel predictor: ONE replica process spanning
            # parallelism.total chips (the serving gang — the engine builds
            # a mesh and GSPMD-shards weights/KV over it). The chip request
            # must cover the mesh; the gang allocator places it like any
            # other multi-chip worker.
            parallelism = pred.parallelism.axis_sizes()
            resources = resources.model_copy(
                update={"tpu_chips": pred.parallelism.total})
        if clone_from is not None:
            # Previous-generation replacement: the isvc spec holds the NEW
            # generation's model — take the stable config AND resources from
            # a surviving sibling of the same generation (fresh port only);
            # the stable model under the canary's resource request could
            # OOM and crash-loop the 100-p traffic share.
            config = dict(clone_from.spec.template.config)
            config["port"] = port
            resources = clone_from.spec.resources
            parallelism = dict(clone_from.spec.parallelism)
        else:
            model = pred.model
            batching = pred.batching.model_dump()
            if role is not None:
                # Pool membership IS the engine role: the replica's
                # engine builds prefill-/decode-specialized.
                batching["role"] = role
            config = {
                "service": model.model_name or isvc.metadata.name,
                "model": model.config or {"preset": "tiny"},
                "storage_uri": model.storage_uri,
                "batching": batching,
                "port": port,
            }
            if isvc.spec.transformer is not None:
                config["transformer"] = isvc.spec.transformer.model_dump()
            if isvc.spec.explainer is not None:
                config["explainer"] = isvc.spec.explainer.model_dump()
        labels = {LABEL_ISVC: isvc.metadata.name,
                  LABEL_REPLICA: str(index),
                  LABEL_GEN: str(generation)}
        name = f"{isvc.metadata.name}-predictor-g{generation}-{index}"
        if role is not None:
            labels[LABEL_ROLE] = role
            name = f"{isvc.metadata.name}-predictor-{role}-{index}"
        w = Worker(
            metadata=ObjectMeta(
                name=name,
                namespace=isvc.metadata.namespace,
                labels=labels,
                owner=isvc.key,
            ),
            spec=WorkerSpec(
                job=isvc.metadata.key,
                replica_index=index,
                num_workers=1,
                template=WorkloadSpec(entrypoint="model_server", config=config),
                resources=resources,
                parallelism=parallelism,
                restart_policy=RestartPolicy.ON_FAILURE,
            ),
            status=WorkerStatus(),
        )
        try:
            created = self.store.create(w)
        except AlreadyExistsError:
            return self.store.get(Worker, w.metadata.name, w.metadata.namespace)
        self.recorder.normal(isvc, "CreatedReplica",
                             f"{w.metadata.name} on port {port}")
        return created

    def _delete_worker(self, w: Worker) -> None:
        try:
            self.store.delete(Worker, w.metadata.name, w.metadata.namespace)
        except NotFoundError:
            pass

    def _update_status(self, isvc: InferenceService) -> None:
        try:
            self.store.update_status(isvc)
        except NotFoundError:
            pass

    def shutdown(self) -> None:
        for router in self._routers.values():
            router.stop()
        self._routers.clear()
        self._draining.clear()
