"""Explainer hop — the third leg of the predictor/transformer/explainer
triad ((U) kserve pkg/apis/serving/v1beta1 ExplainerSpec + the alibi
explainer containers; SURVEY.md §2.3#24-25).

TPU-native shape: instead of a sidecar container wrapping a black-box
model, the explainer differentiates THROUGH the served decoder — JAX makes
the model its own explainer:

- ``grad_x_input``: embedding-gradient × embedding attribution. One
  forward picks the model's predicted next token, one VJP through the
  decoder w.r.t. the *embedded* inputs scores every prompt token's
  contribution to that prediction (the saliency formulation; exact
  directional derivative, finite-difference-tested).
- ``leave_one_out``: occlusion attribution. All S ablations run as ONE
  [S+1, S] batched forward — a large static-shape batch, exactly what the
  MXU wants — scoring each token by how much its removal drops the
  predicted token's log-probability.

Handlers are registered like transformers (name or "module:function"), so
custom explainers plug in without touching the server.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp

explainer_registry: dict[str, Callable] = {}


# Jitted computation per (cfg, mesh): the mesh-mode engine holds TP-sharded
# (possibly int8-quantized) params — an eager forward would dispatch
# primitive-by-primitive over sharded operands; under jit GSPMD partitions
# the whole attribution computation and inserts the per-layer psums exactly
# as serving dispatches do. lru_cache keys on the hashable (cfg, mesh) so
# each served configuration compiles once per prompt length.

@functools.lru_cache(maxsize=32)
def _logits_fn(cfg, mesh):
    from kubeflow_tpu.models.decoder import decoder_forward

    def f(params, toks):
        logits, _, _ = decoder_forward(params, toks, cfg, mesh=mesh)
        return logits

    return jax.jit(f)


@functools.lru_cache(maxsize=32)
def _embed_fn(cfg, mesh):
    def f(params, toks):
        table = params["embed"].astype(cfg.activation_dtype)
        return table[toks]

    return jax.jit(f)


@functools.lru_cache(maxsize=32)
def _saliency_fn(cfg, mesh):
    from kubeflow_tpu.models.decoder import decoder_forward

    def lp_of(params, toks, embeds, target):
        logits, _, _ = decoder_forward(params, toks, cfg, mesh=mesh,
                                       inputs_embeds=embeds)
        return jax.nn.log_softmax(
            logits[0, -1].astype(jnp.float32))[target]

    def f(params, toks, embeds, target):
        g = jax.grad(lp_of, argnums=2)(params, toks, embeds, target)
        return jnp.sum(g.astype(jnp.float32) * embeds.astype(jnp.float32),
                       axis=-1)[0]

    return jax.jit(f)


@functools.lru_cache(maxsize=32)
def _loo_fn(cfg, mesh):
    from kubeflow_tpu.models.decoder import decoder_forward

    def f(params, variants, target):
        logits, _, _ = decoder_forward(params, variants, cfg, mesh=mesh)
        return jax.nn.log_softmax(logits[:, -1].astype(jnp.float32),
                                  axis=-1)[:, target]

    return jax.jit(f)


def register_explainer(name: str):
    def deco(fn: Callable) -> Callable:
        explainer_registry[name] = fn
        return fn
    return deco


def resolve_explainer(handler: str) -> Callable:
    if handler in explainer_registry:
        return explainer_registry[handler]
    module, sep, attr = handler.partition(":")
    if not sep:
        raise KeyError(
            f"explainer {handler!r} is not registered and is not a "
            f"'module:function' path; registered: "
            f"{sorted(explainer_registry)}")
    import importlib

    return getattr(importlib.import_module(module), attr)


def _predicted_target(params, cfg, toks: jax.Array,
                      mesh=None) -> tuple[int, float]:
    """(argmax next token at the last position, its log-probability)."""
    logits = _logits_fn(cfg, mesh)(params, toks)
    lp = jax.nn.log_softmax(logits[0, -1].astype(jnp.float32))
    target = int(jnp.argmax(lp))
    return target, float(lp[target])


@register_explainer("grad_x_input")
def grad_x_input(tokens: list[int], *, params, cfg, mesh=None, **_) -> dict:
    """Saliency: score_i = <d logp(target)/d e_i, e_i> for each prompt
    embedding e_i — the first-order effect of removing token i."""
    toks = jnp.asarray([tokens], jnp.int32)
    target, lp_target = _predicted_target(params, cfg, toks, mesh)
    embeds = _embed_fn(cfg, mesh)(params, toks)      # [1, S, D] (pre-scale)
    scores = _saliency_fn(cfg, mesh)(params, toks, embeds,
                                     jnp.int32(target))
    return {
        "method": "grad_x_input",
        "target_token": target,
        "target_logprob": lp_target,
        "scores": [float(s) for s in scores],
    }


@register_explainer("leave_one_out")
def leave_one_out(tokens: list[int], *, params, cfg, mesh=None,
                  ablate_token: int = 0, **_) -> dict:
    """Occlusion: score_i = logp(target | prompt) - logp(target | prompt
    with token i replaced by ``ablate_token``). One [S+1, S] forward."""
    s = len(tokens)
    toks = jnp.asarray([tokens], jnp.int32)
    target, lp_full = _predicted_target(params, cfg, toks, mesh)
    base = jnp.asarray(tokens, jnp.int32)
    variants = jnp.where(jnp.eye(s, dtype=bool), jnp.int32(ablate_token),
                         base[None, :])              # [S, S]
    lps = _loo_fn(cfg, mesh)(params, variants, jnp.int32(target))
    return {
        "method": "leave_one_out",
        "target_token": target,
        "target_logprob": lp_full,
        "scores": [float(lp_full - v) for v in lps],
    }


def build_explainer(conf: Optional[dict]) -> Optional[Callable]:
    """ExplainerSpec.{handler,config} → callable(tokens, params, cfg) →
    explanation dict. None config = no explainer hop."""
    if not conf:
        return None
    import functools

    fn = resolve_explainer(conf.get("handler", "grad_x_input"))
    if conf.get("config"):
        fn = functools.partial(fn, **conf["config"])
    return fn
