"""Multi-model repository with LRU placement — ModelMesh-lite.

((U) kserve pkg/agent + modelmesh-serving; SURVEY.md §2.3#29.) The reference
pairs a per-pod model *agent* (pull/evict) with ModelMesh's high-density LRU
placement of models across serving pods. TPU-natively the scarce resource is
one chip's HBM: the repository keeps registered models' engines loaded up to
a budget (count and/or estimated bytes) and evicts least-recently-used
engines — their slot KV caches and weights free HBM — reloading on demand.

Serves the v2 repository API through the model server:
``GET /v2/repository/index``, ``POST /v2/repository/models/{m}/load|unload``.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from collections import OrderedDict
from typing import Any, Callable, Optional

from kubeflow_tpu.models.config import DecoderConfig
from kubeflow_tpu.serve.engine import LLMEngine
from kubeflow_tpu.serve.tokenizer import Tokenizer, get_tokenizer

logger = logging.getLogger("kubeflow_tpu.serve")


def estimate_model_bytes(cfg: DecoderConfig, batching=None) -> int:
    """Weights (param dtype; int8-packed accounting when the batching
    spec quantizes — a quantized engine booked at full-dtype bytes reads
    ~2-4x its true residency, so the LRU would evict half the models it
    could actually hold) + the engine's slot KV cache (often dominant
    for small models at long max_seq_len; int8 pools price 1 byte + the
    4/head_dim scale overhead per element) + the packed LoRA adapter
    buffers when the engine serves multi-tenant adapters (serve/lora.py
    — max_adapters slots of rank-r A/B factors per target)."""
    if batching is not None and getattr(batching, "quantize", None):
        from kubeflow_tpu.ops.quantization import packed_param_bytes_estimate

        param_bytes = packed_param_bytes_estimate(cfg)
    else:
        param_bytes = cfg.num_params() * cfg.weight_dtype.itemsize
    kv_bytes = 0
    lora_bytes = 0
    if batching is not None:
        kv_tokens = (2 * cfg.n_layers * batching.max_batch_size
                     * batching.max_seq_len * cfg.n_kv_heads)
        if getattr(batching, "kv_cache_dtype", None) == "int8":
            # int8 page payload + one f32 scale per token per kv head.
            kv_bytes = kv_tokens * (cfg.head_dim + 4)
        else:
            kv_bytes = (kv_tokens * cfg.head_dim
                        * cfg.activation_dtype.itemsize)
        lora = getattr(batching, "lora", None)
        if lora is not None and lora.max_adapters:
            from kubeflow_tpu.serve.lora import target_dims

            per_slot = sum(
                (din + dout) * lora.rank
                for din, dout in (target_dims(cfg, t)
                                  for t in lora.targets))
            lora_bytes = (cfg.n_layers * lora.max_adapters * per_slot
                          * cfg.activation_dtype.itemsize)
    return int(param_bytes * 1.1) + kv_bytes + lora_bytes


@dataclasses.dataclass
class ModelEntry:
    name: str
    cfg: DecoderConfig
    make_engine: Callable[[], LLMEngine]
    tokenizer: Tokenizer
    bytes: int
    engine: Optional[LLMEngine] = None   # None = registered but not loaded
    refs: int = 0                        # in-flight requests holding a lease
    #: engines detached by unload while leased: stopped when refs hit 0
    draining: list = dataclasses.field(default_factory=list)

    @property
    def state(self) -> str:
        return "READY" if self.engine is not None else "UNLOADED"


class ModelRepository:
    """Thread-safe LRU of loaded engines under a capacity budget.

    Loads are serialized (`_load_lock`): engine construction takes seconds
    and double-building on a racing first request would bust the HBM budget.
    In-flight requests hold a *lease* on their entry; eviction skips leased
    engines (temporarily exceeding the budget beats killing live requests)."""

    def __init__(self, *, max_loaded: int = 2,
                 max_bytes: Optional[int] = None):
        self.max_loaded = max_loaded
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        self._load_lock = threading.Lock()
        self._entries: "OrderedDict[str, ModelEntry]" = OrderedDict()

    # -- registration ----------------------------------------------------------

    def register(self, name: str, cfg: DecoderConfig, *,
                 make_engine: Optional[Callable[[], LLMEngine]] = None,
                 tokenizer: Optional[Tokenizer] = None,
                 batching=None) -> ModelEntry:
        if make_engine is None:
            def make_engine(cfg=cfg, batching=batching):
                return LLMEngine(cfg, batching)

        entry = ModelEntry(
            name=name, cfg=cfg, make_engine=make_engine,
            tokenizer=tokenizer or get_tokenizer("byte"),
            bytes=estimate_model_bytes(cfg, batching))
        with self._lock:
            self._entries[name] = entry
        return entry

    def names(self) -> list[str]:
        with self._lock:
            return list(self._entries)

    def index(self) -> list[dict[str, Any]]:
        with self._lock:
            entries = list(self._entries.values())
        out = []
        for e in entries:
            row: dict[str, Any] = {"name": e.name, "state": e.state,
                                   "bytes": e.bytes}
            # Multi-tenant LoRA: surface the loaded engine's hot
            # adapters so the repository index shows which VARIANTS
            # this replica can serve without a hot-load.
            engine = e.engine
            if engine is not None and getattr(engine, "_lora", None) \
                    is not None:
                row["adapters_resident"] = engine.adapters_resident()
            out.append(row)
        return out

    def peek(self, name: str) -> Optional[ModelEntry]:
        """Entry without loading or touching LRU recency (metadata/metrics)."""
        with self._lock:
            return self._entries.get(name)

    # -- load/unload/eviction --------------------------------------------------

    def _loaded_locked(self) -> list[ModelEntry]:
        return [e for e in self._entries.values() if e.engine is not None]

    def _evict_for_locked(self, incoming: ModelEntry
                          ) -> list[tuple[ModelEntry, LLMEngine]]:
        """LRU-evict (OrderedDict order = recency, oldest first) until the
        incoming model fits, skipping leased entries. Detaches victim
        engines under the lock; returns them to stop outside it."""
        victims: list[tuple[ModelEntry, LLMEngine]] = []

        def over() -> bool:
            loaded = [e for e in self._loaded_locked()]
            if len(loaded) + 1 > self.max_loaded:
                return True
            if self.max_bytes is not None:
                used = sum(e.bytes for e in loaded)
                return used + incoming.bytes > self.max_bytes
            return False

        for e in list(self._entries.values()):      # oldest-touched first
            if not over():
                break
            if e.engine is not None and e.name != incoming.name \
                    and e.refs == 0:
                engine, e.engine = e.engine, None
                victims.append((e, engine))
        return victims

    def load(self, name: str) -> LLMEngine:
        """Load (or touch) a registered model; may evict idle LRU engines."""
        with self._lock:
            if name not in self._entries:
                raise KeyError(f"model {name!r} is not registered")
            entry = self._entries[name]
            self._entries.move_to_end(name)         # touch (most recent)
            if entry.engine is not None:
                return entry.engine
        # Serialize builds: a racing first request must not double-build.
        with self._load_lock:
            with self._lock:
                if entry.engine is not None:        # loaded while we waited
                    return entry.engine
                victims = self._evict_for_locked(entry)
            for v, engine in victims:
                logger.info("evicting model %s (LRU)", v.name)
                engine.stop()
            engine = entry.make_engine()
            engine.start()
            with self._lock:
                entry.engine = engine
        logger.info("loaded model %s (%.1f MB est.)", name,
                    entry.bytes / 1e6)
        return engine

    def unload(self, name: str) -> None:
        """Detach the model. Leased in-flight requests keep their engine
        alive (it drains and stops when the last lease releases) — unload
        must not kill live requests any more than LRU eviction does."""
        with self._lock:
            entry = self._entries.get(name)
            if entry is None:
                raise KeyError(f"model {name!r} is not registered")
            engine, entry.engine = entry.engine, None
            if engine is not None and entry.refs > 0:
                entry.draining.append(engine)
                engine = None
        if engine is not None:
            engine.stop()

    def acquire(self, name: str) -> ModelEntry:
        """Lease an entry for one request: loads on demand and pins the
        engine against eviction until release()."""
        self.load(name)
        with self._lock:
            entry = self._entries[name]
            if entry.engine is None:
                # unloaded between load and lease (explicit unload): retry
                pass
            else:
                entry.refs += 1
                return entry
        return self.acquire(name)

    def release(self, entry: ModelEntry) -> None:
        drained: list = []
        with self._lock:
            entry.refs = max(0, entry.refs - 1)
            if entry.refs == 0 and entry.draining:
                drained, entry.draining = entry.draining, []
        for engine in drained:
            engine.stop()

    def get(self, name: str) -> ModelEntry:
        """Entry for serving: loads on demand (the model-agent pull path).
        Prefer acquire()/release() for request-scoped use."""
        self.load(name)
        with self._lock:
            return self._entries[name]

    def shutdown(self) -> None:
        for name in self.names():
            try:
                self.unload(name)
            except KeyError:
                pass
