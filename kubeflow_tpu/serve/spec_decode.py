"""Speculative decoding — draft + batched verify over the slot/page caches.

Why it wins on v5e: a decode step is dispatch- and HBM-bound (the whole
param read for ONE token per slot), so scoring k+1 positions per slot in a
single dispatch costs barely more than scoring one — the params are read
once either way. If a cheap drafter can guess the next k tokens, greedy
verification accepts the longest prefix that matches the target's own
argmax and emits one extra "correction" token from the position that broke
the match, so every round emits between 1 and k+1 tokens at output
TOKEN-IDENTICAL to plain greedy decode (the accepted tokens ARE the
target's argmax chain by construction).

Two draft sources (core/serving.py ``SpeculativeSpec``):

- **ngram** (prompt/self lookup, vLLM's ``ngram`` analog): match the last
  n-gram of prompt+generated against its own earlier occurrences and
  propose the continuation that followed. Free (no model), and strong
  exactly where serving traffic is decode-heavy: templated suffixes,
  extraction, code, and greedy generations that fall into repeating cycles.
- **draft_model**: a small decoder (same vocab) runs ``k`` autoregressive
  steps per round against its OWN dense slot cache; the target verifies.
  The draft cache tracks the true sequence via a per-slot consumed-length
  pointer — on rejection the pointer rewinds (draft KV past it is garbage
  but every position is rewritten before it is ever attended, the same
  overwrite-before-read invariant the decode caches already rely on).

Verification is exact for GREEDY requests only (argmax chains compose);
the engine falls back to the normal decode path whenever a sampling
request shares the batch.

KV rollback: the verify dispatch writes K/V for all k+1 positions before
acceptance is known. Rejected positions hold garbage — harmless in the
dense cache (overwritten before read), while the paged engine additionally
truncates each slot's page table back to the accepted length
(engine._truncate_slot_pages) so the pool's refcounts always account for
exactly the tokens a slot actually kept.

Scheduler-state residency: ``paged_verify_step`` consumes the SAME
device-resident page table the plain decode path owns
(serve/device_state.py) — the engine syncs dirty rows as deltas and
donates the table through the dispatch, so a verify round never re-uploads
the full table. The ``[B, T]`` token matrix and the ``[B]`` lengths/live
masks are inherently per-round host data (the drafts were proposed on
host), and rollback marks the affected rows dirty for the next sync.
Because verification is a host-side decision between dispatches, spec
rounds do not pipeline — the engine drains any in-flight plain round
before entering a spec round.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from kubeflow_tpu.models import layers as L
from kubeflow_tpu.models.config import DecoderConfig
from kubeflow_tpu.models.decoder import Params


# -- drafting ------------------------------------------------------------------

def ngram_propose(ctx: Sequence[int], k: int, ngram_max: int,
                  ngram_min: int) -> list[int]:
    """Prompt/self-lookup drafting: find the most recent earlier occurrence
    of the context's last n-gram (longest n first) and propose the up-to-k
    tokens that followed it. Returns [] when nothing matches — the engine
    then decodes that slot normally (a wrong draft costs a wasted verify
    column; no draft costs nothing)."""
    ln = len(ctx)
    for n in range(min(ngram_max, ln - 1), ngram_min - 1, -1):
        pat = tuple(ctx[ln - n:])
        # rightmost earlier occurrence: recent history predicts the
        # immediate future better than the distant past
        for i in range(ln - n - 1, -1, -1):
            if tuple(ctx[i:i + n]) == pat:
                out = list(ctx[i + n:i + n + k])
                if out:
                    return out
                break       # match flush against the suffix: nothing follows
    return []


# -- batched verify (dense slot cache) -----------------------------------------

def _spec_attention(q, ck, cv, lengths, cfg: DecoderConfig):  # traced
    """T-query attention over slot caches (the verify-length generalization
    of engine._decode_attention). q [B,T,H,Dh]; ck/cv [B,Smax,KV,Dh];
    query t sits at position lengths[b]+t and attends kpos <= that."""
    b, t = q.shape[0], q.shape[1]
    smax = ck.shape[1]
    groups = cfg.n_heads // cfg.n_kv_heads
    qg = q.reshape(b, t, cfg.n_kv_heads, groups, cfg.head_dim)
    scores = jnp.einsum("btkgd,bskd->btkgs", qg, ck,
                        preferred_element_type=jnp.float32)
    scores *= cfg.head_dim ** -0.5
    kpos = jnp.arange(smax, dtype=jnp.int32)
    qpos = lengths[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]
    mask = kpos[None, None, :] <= qpos[:, :, None]            # [B,T,Smax]
    scores = jnp.where(mask[:, :, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(ck.dtype)
    out = jnp.einsum("btkgs,bskd->btkgd", probs, cv)
    return out.reshape(b, t, cfg.n_heads, cfg.head_dim)


def _spec_block(bp, x, positions, lengths, live, cache_k, cache_v,  # traced
                cfg: DecoderConfig):
    """One transformer block for a [B,T] verify step against slot caches
    (engine._decode_block with a verify-length axis). Writes the K/V of all
    T tokens at positions[b, t]; dead rows and out-of-range positions aim
    out of bounds and DROP."""
    dt = cfg.activation_dtype
    h = L.rmsnorm(x, bp["ln1"], cfg)
    q = jnp.einsum("bsd,dhk->bshk", h, bp["attn"]["wq"].astype(dt))
    k = jnp.einsum("bsd,dhk->bshk", h, bp["attn"]["wk"].astype(dt))
    v = jnp.einsum("bsd,dhk->bshk", h, bp["attn"]["wv"].astype(dt))
    q = L.rope(q, positions, cfg.rope_theta)
    k = L.rope(k, positions, cfg.rope_theta)
    smax = cache_k.shape[1]
    bidx = jnp.arange(x.shape[0])[:, None]
    widx = jnp.where(live[:, None] & (positions < smax), positions, smax)
    ck = cache_k.at[bidx, widx].set(k, mode="drop")
    cv = cache_v.at[bidx, widx].set(v, mode="drop")
    attn = _spec_attention(q, ck, cv, lengths, cfg)
    x = x + jnp.einsum("bshk,hkd->bsd", attn, bp["attn"]["wo"].astype(dt))
    h = L.rmsnorm(x, bp["ln2"], cfg)
    if cfg.is_moe:
        mlp_out, _ = L.moe_block(bp["mlp"], h, cfg)
    else:
        mlp_out = L.mlp_block(bp["mlp"], h, cfg)
    return x + mlp_out, ck, cv


def verify_step(params: Params, cache: dict, tokens: jax.Array,  # traced
                lengths: jax.Array, live: jax.Array, cfg: DecoderConfig):
    """ONE dispatch scoring T = k+1 positions per slot over the dense slot
    cache. tokens [B,T] = [last_token, draft_1..draft_k] (pad columns are
    scored too — the host just ignores them); lengths [B] = the write
    position of tokens[:,0], exactly as in engine._decode_step.

    Returns ([B,T] int32 greedy next-token ids, new cache): row b column t
    is the target's argmax continuation after consuming tokens[b, :t+1] —
    the verification oracle for draft t+1 and the correction/bonus token
    when the match breaks there."""
    dt = cfg.activation_dtype
    t = tokens.shape[1]
    x = params["embed"].astype(dt)[tokens]                    # [B,T,D]
    if cfg.embed_scale:
        x = x * jnp.asarray(cfg.hidden ** 0.5, dt)
    positions = lengths[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]

    def body(x, scan_in):
        bp, ck, cv = scan_in
        x, nk, nv = _spec_block(bp, x, positions, lengths, live, ck, cv, cfg)
        return x, (nk, nv)

    x, (nk, nv) = jax.lax.scan(body, x, (params["layers"],
                                         cache["k"], cache["v"]))
    x = L.rmsnorm(x, params["final_norm"], cfg)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("btd,dv->btv", x, head.astype(dt),
                        preferred_element_type=jnp.float32)
    if cfg.logits_softcap is not None:
        logits = jnp.tanh(logits / cfg.logits_softcap) * cfg.logits_softcap
    return jnp.argmax(logits, axis=-1).astype(jnp.int32), {"k": nk, "v": nv}


# -- batched verify (paged pool) -----------------------------------------------

def _paged_spec_block(bp, x, positions, lengths, live, pool_k, pool_v,  # traced
                      table, cfg: DecoderConfig, pool_ks=None, pool_vs=None):
    """Verify block against the page pool (paged._paged_decode_block with a
    verify-length axis; always the gather attention impl — the Pallas
    paged-attention kernel is single-query). Position -> (page, offset)
    per token; unmapped pages, dead rows and positions past the table's
    reach aim out of bounds and DROP."""
    from kubeflow_tpu.serve.paged import paged_gather

    dt = cfg.activation_dtype
    kv_quant = pool_ks is not None
    pg = pool_k.shape[1]
    mpp = table.shape[1]
    h = L.rmsnorm(x, bp["ln1"], cfg)
    q = jnp.einsum("bsd,dhk->bshk", h, bp["attn"]["wq"].astype(dt))
    k = jnp.einsum("bsd,dhk->bshk", h, bp["attn"]["wk"].astype(dt))
    v = jnp.einsum("bsd,dhk->bshk", h, bp["attn"]["wv"].astype(dt))
    q = L.rope(q, positions, cfg.rope_theta)
    k = L.rope(k, positions, cfg.rope_theta)
    bidx = jnp.arange(x.shape[0])[:, None]                    # [B,1]
    page_slot = positions // pg                               # [B,T]
    page_id = table[bidx, jnp.clip(page_slot, 0, mpp - 1)]
    ok = live[:, None] & (page_id >= 0) & (positions < mpp * pg)
    pidx = jnp.where(ok, page_id, pool_k.shape[0])
    off = positions % pg
    nks = nvs = None
    if kv_quant:
        from kubeflow_tpu.ops.quantization import dequantize_kv, quantize_kv

        kq, ks = quantize_kv(k)
        vq, vs = quantize_kv(v)
        nk = pool_k.at[pidx, off].set(kq, mode="drop")
        nv = pool_v.at[pidx, off].set(vq, mode="drop")
        nks = pool_ks.at[pidx, off].set(ks, mode="drop")
        nvs = pool_vs.at[pidx, off].set(vs, mode="drop")
        ck = dequantize_kv(paged_gather(nk, table),
                           paged_gather(nks, table), dt)
        cv = dequantize_kv(paged_gather(nv, table),
                           paged_gather(nvs, table), dt)
    else:
        nk = pool_k.at[pidx, off].set(k, mode="drop")
        nv = pool_v.at[pidx, off].set(v, mode="drop")
        ck = paged_gather(nk, table)
        cv = paged_gather(nv, table)
    attn = _spec_attention(q, ck, cv, lengths, cfg)
    x = x + jnp.einsum("bshk,hkd->bsd", attn, bp["attn"]["wo"].astype(dt))
    h = L.rmsnorm(x, bp["ln2"], cfg)
    if cfg.is_moe:
        mlp_out, _ = L.moe_block(bp["mlp"], h, cfg)
    else:
        mlp_out = L.mlp_block(bp["mlp"], h, cfg)
    return x + mlp_out, nk, nv, nks, nvs


def paged_verify_step(params: Params, cache: dict, tokens: jax.Array,  # traced
                      lengths: jax.Array, live: jax.Array,
                      cfg: DecoderConfig):
    """verify_step over the page pool (cache carries "table"; the host
    pre-allocates pages covering all T write positions, exactly like
    paged_decode_multi's contract). Returns ([B,T] greedy ids, cache)."""
    dt = cfg.activation_dtype
    kv_quant = "ks" in cache
    t = tokens.shape[1]
    x = params["embed"].astype(dt)[tokens]
    if cfg.embed_scale:
        x = x * jnp.asarray(cfg.hidden ** 0.5, dt)
    positions = lengths[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]
    table = cache["table"]

    if kv_quant:
        def body(x, scan_in):
            bp, pk, pv, pks, pvs = scan_in
            x, nk, nv, nks, nvs = _paged_spec_block(
                bp, x, positions, lengths, live, pk, pv, table, cfg,
                pool_ks=pks, pool_vs=pvs)
            return x, (nk, nv, nks, nvs)

        x, scanned = jax.lax.scan(
            body, x, (params["layers"], cache["k"], cache["v"],
                      cache["ks"], cache["vs"]))
    else:
        def body(x, scan_in):
            bp, pk, pv = scan_in
            x, nk, nv, _, _ = _paged_spec_block(
                bp, x, positions, lengths, live, pk, pv, table, cfg)
            return x, (nk, nv)

        x, scanned = jax.lax.scan(
            body, x, (params["layers"], cache["k"], cache["v"]))
    x = L.rmsnorm(x, params["final_norm"], cfg)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("btd,dv->btv", x, head.astype(dt),
                        preferred_element_type=jnp.float32)
    if cfg.logits_softcap is not None:
        logits = jnp.tanh(logits / cfg.logits_softcap) * cfg.logits_softcap
    out = {"k": scanned[0], "v": scanned[1], "table": table}
    if kv_quant:
        out["ks"], out["vs"] = scanned[2], scanned[3]
    return jnp.argmax(logits, axis=-1).astype(jnp.int32), out


# -- draft-model proposal ------------------------------------------------------

def draft_propose(params: Params, cache: dict, deltas: jax.Array,  # traced
                  delta_lens: jax.Array, draft_pos: jax.Array,
                  live: jax.Array, cfg: DecoderConfig, num_steps: int):
    """Catch-up + autoregressive drafting for the small model in ONE
    dispatch of ``num_steps`` single-token decode steps over its dense slot
    cache (engine._decode_step reused verbatim — the draft is just another
    decoder).

    Per slot b: steps t < delta_lens[b] feed deltas[b, t] (the true tokens
    the draft hasn't consumed yet — the previous round's accepted suffix);
    later steps feed the draft's own greedy prediction from the step
    before. Every step's argmax lands in out[:, t]; the host reads slot
    b's k drafts at columns delta_lens[b]-1 .. delta_lens[b]-1+k-1.

    Returns (out [B, num_steps] int32, new cache)."""
    from kubeflow_tpu.serve.engine import _decode_step

    b = deltas.shape[0]
    dmax = deltas.shape[1]
    max_len = cache["k"].shape[2]

    def body(carry, t):
        cache, prev = carry
        fed = jnp.where(t < delta_lens,
                        deltas[:, jnp.clip(t, 0, dmax - 1)], prev)
        lengths = draft_pos + t
        step_live = live & (lengths < max_len)
        logits, cache = _decode_step(params, cache, fed, lengths,
                                     step_live, cfg)
        g = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (cache, g), g

    (cache, _), outs = jax.lax.scan(
        body, (cache, jnp.zeros((b,), jnp.int32)),
        jnp.arange(num_steps, dtype=jnp.int32))
    return outs.T, cache
