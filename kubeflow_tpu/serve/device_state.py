"""Device-resident decode scheduler state — the host-overhead half of the
hot-loop elimination (ISSUE 4 tentpole (a)).

Before this module, every decode round re-materialised the scheduler's
tensor-shaped state from host Python: eight ``[B]`` arrays
(tokens/lengths/live/temps/top_k/top_p/stops/budgets) rebuilt with numpy and
``jnp.asarray``-uploaded per dispatch, plus — in paged mode — the FULL
``[B, max_pages_per_slot]`` page table. On a tunneled chip each of those
uploads rides the same ~16 ms round-trip the multi-step dispatch exists to
amortize, and the re-materialisation itself is host work serialized against
device compute.

Here the state lives on device, owned by the engine for the engine's
lifetime:

- **One full upload, ever** (per array, at construction). The counter in
  ``stats`` proves it: steady-state decode rounds perform ZERO full-array
  host→device uploads of scheduler state (``tests/test_serve_hotloop.py``
  asserts the counters stay flat while rounds accumulate).
- **Deltas, not snapshots.** Host-side scheduler events (admission into a
  slot, reap/cancel, preemption, a speculative round advancing a slot,
  page-table growth) mark the slot/row DIRTY; immediately before the next
  dispatch the engine flushes each dirty index through a small donated
  ``jit`` scatter — a handful of scalars (or one ``[mpp]`` row) per changed
  slot, instead of the whole batch every round.
- **The device is the mirror master in steady state.** The decode dispatch
  itself consumes the state and returns the advanced state (same donated
  buffers); because the device applies the exact finish rules the host
  scheduler does (stop token, budget, cache edge), a slot that decodes
  without host interference never needs a sync at all.

The dirty-set discipline (who marks what) lives in ``serve/engine.py``;
this module is the mechanism: the arrays, the scatter programs, and the
upload accounting.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

#: Per-slot scheduler state riding into every decode dispatch, in scatter
#: order. ``tokens`` = last sampled token (the next step's input);
#: ``lengths`` = its KV write position; ``live`` masks dead rows; the rest
#: are per-slot sampling params and the remaining token budget.
STATE_FIELDS = ("tokens", "lengths", "live", "temps", "top_k", "top_p",
                "stops", "budgets", "adapter")

_DTYPES = {"tokens": jnp.int32, "lengths": jnp.int32, "live": jnp.bool_,
           "temps": jnp.float32, "top_k": jnp.int32, "top_p": jnp.float32,
           "stops": jnp.int32, "budgets": jnp.int32, "adapter": jnp.int32}

#: Values a freed slot scatters back to (live=False is the one that
#: matters — a dead row's other fields are never read by the dispatch).
DEAD_SLOT = (0, 0, False, 0.0, 0, 1.0, -1, 0, -1)


def _scatter_slot(arrays: dict, idx, tok, length, live, temp, tk, tp,
                  stop, budget, adapter) -> dict:
    """One slot's state delta as a scatter at ``idx`` (donated in/out)."""
    return {
        "tokens": arrays["tokens"].at[idx].set(tok),
        "lengths": arrays["lengths"].at[idx].set(length),
        "live": arrays["live"].at[idx].set(live),
        "temps": arrays["temps"].at[idx].set(temp),
        "top_k": arrays["top_k"].at[idx].set(tk),
        "top_p": arrays["top_p"].at[idx].set(tp),
        "stops": arrays["stops"].at[idx].set(stop),
        "budgets": arrays["budgets"].at[idx].set(budget),
        "adapter": arrays["adapter"].at[idx].set(adapter),
    }


class DecodeState:
    """Persistent on-device scheduler state + dirty-index delta sync.

    ``arrays`` is the dict of eight ``[B]`` device arrays the decode
    dispatch donates and returns; ``table`` (paged engines only) is the
    ``[B, mpp]`` device page table threaded through paged dispatches the
    same way. ``adopt()`` swaps in a dispatch's returned handles; the
    ``mark_*``/``sync_*`` pair applies host-side scheduler deltas as
    per-index donated scatters."""

    def __init__(self, num_slots: int, mpp: Optional[int] = None):
        self.num_slots = num_slots
        self.arrays: dict[str, jax.Array] = {
            "tokens": jnp.zeros((num_slots,), jnp.int32),
            "lengths": jnp.zeros((num_slots,), jnp.int32),
            "live": jnp.zeros((num_slots,), jnp.bool_),
            "temps": jnp.zeros((num_slots,), jnp.float32),
            "top_k": jnp.zeros((num_slots,), jnp.int32),
            "top_p": jnp.ones((num_slots,), jnp.float32),
            "stops": jnp.full((num_slots,), -1, jnp.int32),
            "budgets": jnp.zeros((num_slots,), jnp.int32),
            # Multi-tenant LoRA (serve/lora.py): the packed-buffer slot
            # whose low-rank delta applies to this row; -1 = base model.
            "adapter": jnp.full((num_slots,), -1, jnp.int32),
        }
        self.table: Optional[jax.Array] = None
        if mpp is not None:
            self.table = jnp.full((num_slots, mpp), -1, jnp.int32)
        # Upload accounting — the tentpole's proof obligation. "full"
        # counters may only ever reflect construction; sync counters grow
        # with scheduler events, never with steady-state decode rounds.
        self.stats = {
            "full_state_uploads": 1,
            "full_table_uploads": 1 if mpp is not None else 0,
            "slot_syncs": 0,
            "table_row_syncs": 0,
        }
        self.dirty_slots: set[int] = set()
        self.dirty_rows: set[int] = set()
        self._scatter = jax.jit(_scatter_slot, donate_argnums=(0,))
        self._row_set = jax.jit(lambda t, i, row: t.at[i].set(row),
                                donate_argnums=(0,))

    # -- dirty marking (host scheduler events) -----------------------------

    def mark_slot(self, idx: int) -> None:
        self.dirty_slots.add(idx)

    def mark_slots(self, idxs) -> None:
        self.dirty_slots.update(idxs)

    def mark_row(self, idx: int) -> None:
        if self.table is not None:
            self.dirty_rows.add(idx)

    # -- delta sync (immediately before a dispatch that reads the state) ---

    def sync_slots(self, values_for: Callable[[int], tuple]) -> None:  # hot-loop
        """Scatter every dirty slot's current host-side values.
        ``values_for(idx)`` returns the STATE_FIELDS tuple (DEAD_SLOT for a
        freed slot). Scalars upload via EXPLICIT ``jax.device_put`` so the
        sync stays legal under ``jax.transfer_guard("disallow")`` (the
        KFTPU_SANITIZE runtime guard, and the steady-state guard the
        hot-loop tests apply): every intended transfer is explicit and
        accounted; an implicit one anywhere is a regression. (In this
        jax, ``jnp.asarray`` of a *scalar* still counts as implicit —
        only ``device_put`` is unconditionally explicit.)"""
        put = jax.device_put
        for idx in sorted(self.dirty_slots):
            (tok, length, live, temp, tk, tp, stop, budget,
             adapter) = values_for(idx)
            self.arrays = self._scatter(
                self.arrays, put(np.int32(idx)),
                put(np.int32(tok)), put(np.int32(length)),
                put(np.bool_(live)), put(np.float32(temp)),
                put(np.int32(tk)), put(np.float32(tp)),
                put(np.int32(stop)), put(np.int32(budget)),
                put(np.int32(adapter)))
            self.stats["slot_syncs"] += 1
        self.dirty_slots.clear()

    def sync_rows(self, row_for: Callable[[int], np.ndarray]) -> None:  # hot-loop
        """Scatter every dirty page-table row (one ``[mpp]`` upload each —
        page-table GROWTH costs one row, never the full table)."""
        if self.table is None:
            self.dirty_rows.clear()
            return
        for idx in sorted(self.dirty_rows):
            self.table = self._row_set(
                self.table, jax.device_put(np.int32(idx)),
                jax.device_put(np.ascontiguousarray(row_for(idx),
                                                    np.int32)))
            self.stats["table_row_syncs"] += 1
        self.dirty_rows.clear()

    # -- post-dispatch adoption --------------------------------------------

    def adopt(self, arrays: dict, table: Optional[jax.Array] = None) -> None:
        """Swap in the advanced state a decode dispatch returned (the
        donated buffers' successors). Deltas applied after this chain onto
        the dispatch's outputs — JAX's program-order queueing keeps the
        one-round-deep pipeline coherent without host synchronization."""
        self.arrays = arrays
        if table is not None:
            self.table = table
