"""Service router: one stable URL per InferenceService, weighted backend
selection, and activator-style request queueing — the in-process analog of
the Istio VirtualService + Knative revision traffic split AND the Knative
activator the reference wires per service ((U) kserve
pkg/controller/v1beta1/inferenceservice/components/predictor.go; SURVEY.md
§3.2 'istio-ingress → (serverless: activator→KPA scale 0→1) → queue-proxy'
hop, collapsed to one proxy).

Scale-to-zero: with no ready backends a request does NOT 503 — it parks on a
condition variable and the ``pending`` gauge rises; the ISVC controller
reads that gauge as the activation signal, spawns a replica, and the next
``set_backends`` wakes every parked request (0→1 cold start). 503 only after
``queue_timeout``."""

from __future__ import annotations

import itertools
import random
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class Router:
    """Weighted HTTP proxy over predictor replicas.

    Backends are registered per traffic group (e.g. "latest"/"previous"
    during a canary rollout), each group with a weight percent; requests
    pick a group by weight, then round-robin inside it."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 queue_timeout: float = 120.0):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._groups: dict[str, list[str]] = {}    # group -> base urls
        self._weights: dict[str, int] = {}         # group -> percent
        self._rr = itertools.count()
        self._pending = 0
        self._last_activity = 0.0   # monotonic; stamped per request
        self._closed = False
        self.queue_timeout = queue_timeout
        self.httpd = ThreadingHTTPServer((host, port), _make_handler(self))
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def set_backends(self, groups: dict[str, list[str]],
                     weights: Optional[dict[str, int]] = None) -> None:
        with self._cond:
            self._groups = {g: list(urls) for g, urls in groups.items() if urls}
            if weights:
                self._weights = dict(weights)
            else:
                self._weights = {g: 100 // max(len(self._groups), 1)
                                 for g in self._groups}
            if self._groups:
                self._cond.notify_all()   # wake cold-start queued requests

    @property
    def pending(self) -> int:
        """Requests parked waiting for a backend (the activation signal)."""
        with self._lock:
            return self._pending

    @property
    def last_activity(self) -> float:
        """Monotonic timestamp of the most recent request arrival or
        completion through this router. The KPA-analog idle clock counts
        from here — from *traffic*, not from scale events — so a replica
        that just answered a request (however slow the cold start was) is
        guaranteed a full quiet cooldown before it can be culled."""
        with self._lock:
            return self._last_activity

    def note_activity(self) -> None:
        with self._lock:
            self._last_activity = time.monotonic()

    def _pick_locked(self) -> Optional[str]:
        groups = [(g, self._weights.get(g, 0)) for g in self._groups]
        if not groups:
            return None
        total = sum(w for _, w in groups) or len(groups)
        r = random.uniform(0, total)
        acc = 0.0
        chosen = groups[-1][0]
        for g, w in groups:
            acc += w if total else 1
            if r <= acc:
                chosen = g
                break
        urls = self._groups[chosen]
        return urls[next(self._rr) % len(urls)]

    def pick(self) -> Optional[str]:
        with self._lock:
            return self._pick_locked()

    def pick_or_wait(self, timeout: Optional[float] = None) -> Optional[str]:
        """Pick a backend, queueing until one registers (scale-from-zero
        path). Returns None only after ``timeout`` (default: the router's
        queue_timeout) with still no backend."""
        deadline = time.monotonic() + (
            timeout if timeout is not None else self.queue_timeout)
        with self._cond:
            backend = self._pick_locked()
            if backend is not None:
                return backend
            self._pending += 1
            try:
                while not self._closed:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                    self._cond.wait(remaining)
                    backend = self._pick_locked()
                    if backend is not None:
                        return backend
                return None   # router torn down: fail fast, don't hold 120s
            finally:
                self._pending -= 1

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def start(self) -> None:
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True, name="router")
        self._thread.start()

    def stop(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()   # release every parked request
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)


def _make_handler(router: Router):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *args) -> None:
            pass

        def _proxy(self) -> None:
            router.note_activity()
            try:
                self._proxy_inner()
            finally:
                # Stamp at COMPLETION too: a request slower than the idle
                # cooldown (e.g. a cold start that had to spawn + compile)
                # must restart the clock when it answers, or the replica
                # gets culled the moment in_flight drops back to zero.
                router.note_activity()

        def _proxy_inner(self) -> None:
            backend = router.pick_or_wait()
            if backend is None:
                data = b'{"error": "no ready backends (queue timeout)"}'
                self.send_response(503)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
                return
            n = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(n) if n else None
            req = urllib.request.Request(
                backend + self.path, data=body, method=self.command,
                headers={"Content-Type":
                         self.headers.get("Content-Type", "application/json")})
            try:
                with urllib.request.urlopen(req, timeout=600) as resp:
                    self.send_response(resp.status)
                    ctype = resp.headers.get("Content-Type", "application/json")
                    self.send_header("Content-Type", ctype)
                    if "event-stream" in ctype:
                        self.send_header("Transfer-Encoding", "chunked")
                        self.end_headers()
                        while True:
                            piece = resp.read(512)
                            if not piece:
                                break
                            self.wfile.write(f"{len(piece):x}\r\n".encode()
                                             + piece + b"\r\n")
                            self.wfile.flush()
                        self.wfile.write(b"0\r\n\r\n")
                    else:
                        data = resp.read()
                        self.send_header("Content-Length", str(len(data)))
                        self.end_headers()
                        self.wfile.write(data)
            except urllib.error.HTTPError as exc:
                data = exc.read()
                self.send_response(exc.code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            except OSError as exc:
                data = f'{{"error": "backend unreachable: {exc}"}}'.encode()
                self.send_response(502)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        do_GET = _proxy
        do_POST = _proxy

    return Handler
