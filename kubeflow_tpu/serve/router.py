"""Service router: one stable URL per InferenceService, weighted backend
selection, and activator-style request queueing — the in-process analog of
the Istio VirtualService + Knative revision traffic split AND the Knative
activator the reference wires per service ((U) kserve
pkg/controller/v1beta1/inferenceservice/components/predictor.go; SURVEY.md
§3.2 'istio-ingress → (serverless: activator→KPA scale 0→1) → queue-proxy'
hop, collapsed to one proxy).

Scale-to-zero: with no ready backends a request does NOT 503 — it parks on a
condition variable and the ``pending`` gauge rises; the ISVC controller
reads that gauge as the activation signal, spawns a replica, and the next
``set_backends`` wakes every parked request (0→1 cold start). 503 only after
``queue_timeout``.

Request-lifecycle hardening (Envoy-analog, TPU-native):

- **Deadline-aware timeouts.** The client's remaining budget rides in the
  ``X-Kftpu-Deadline-Ms`` header (default: ``upstream_timeout``); it bounds
  every upstream socket wait — replacing the old hard-coded 600 s — and the
  remaining budget is re-stamped onto the forwarded request so the backend
  engine can reap the request when the client is already gone.
- **Connect-failure retries.** A backend that refuses the connection (zero
  response bytes, nothing reached a model OR the client) is retried on a
  different backend up to ``max_retries`` times.
- **Outlier ejection.** ``eject_threshold`` consecutive failures (connect
  failures or 5xx responses) eject a backend for ``eject_period`` seconds;
  after the window expires the next pick half-opens it — ONE probe request
  (picking re-arms the window so concurrent traffic keeps avoiding it) and
  a success fully reinstates it. If every backend is ejected the router
  panic-routes to the least-recently-ejected one: a suspect backend beats
  queueing into a guaranteed timeout.
- **Draining.** ``set_draining(url)`` removes a backend from selection
  without touching its in-flight requests — the graceful scale-down path
  the ISVC controller drives.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import random
import sys
import threading
import time
import traceback
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

# Header names come from the one module that owns every X-Kftpu-* name
# (core/headers.py); DEADLINE_HEADER/QOS_HEADER are re-exported here for
# the router's historical importers (scripts, tests, grpc_server).
from kubeflow_tpu.core.headers import (
    DEADLINE_HEADER, DECODE_ALTS_HEADER, DECODE_BACKEND_HEADER,
    MODEL_HEADER, QOS_HEADER, TRACE_HEADER,
)
from kubeflow_tpu.obs.registry import (
    MetricsRegistry, contract_note_header, contract_note_series,
    parse_exposition,
)
from kubeflow_tpu.obs.fleet import (
    ROUTER_SPANS_EXPORT_PATH, spans_export_payload,
)
from kubeflow_tpu.obs.trace import debug_traces_payload, get_tracer
from kubeflow_tpu.serve.retry import PROBE_POLICY, call_with_retry

#: Engine series the token-aware router scrapes off every pooled
#: backend's /metrics for placement — the router's half of the
#: engine↔router metrics contract (X7xx two-sided, like the
#: autoscaler's ``_PROBE_SERIES``): prefills place on
#: least-pending-prefill-tokens, decodes on least-REFERENCED-KV-pages,
#: in-flight breaks ties (and stands in for pages on dense engines,
#: which always report zero resident pages). ``kv_pages_resident`` is
#: the resident-REFERENCED gauge (tiered KV cache split): ref-0 cached
#: prefix content is freely evictable and must not read as decode
#: load, so the router also scrapes ``kv_pages_cached`` and prefers —
#: between equally-loaded decode backends — the one holding MORE
#: cached prefix content (its prefix-hit odds are higher).
#: ``kv_pages_remote`` (fleet-wide KV fabric, ISSUE 17) is scraped so
#: placement can see how much of a backend's prefix content already
#: spilled to the shared remote tier — informational today (any replica
#: can promote remote pages), but it keeps the gauge two-sided.
ROUTER_SCRAPE_SERIES = (
    "kftpu_engine_pending_prefill_tokens",
    "kftpu_engine_kv_pages_resident",
    "kftpu_engine_kv_pages_cached",
    "kftpu_engine_kv_pages_remote",
    "kftpu_engine_adapters_resident",
    "kftpu_serving_in_flight",
)


def _rendezvous(key: str, url: str) -> int:
    """Rendezvous (highest-random-weight) score of ``url`` for an
    affinity ``key``: every router instance independently agrees on the
    same preferred backend with no shared state, and removing a backend
    only remaps the keys that hashed to it (no global reshuffle)."""
    digest = hashlib.sha256(f"{key}|{url}".encode()).digest()
    return int.from_bytes(digest[:8], "big")


def _affinity_key(path: str, body: Optional[bytes]) -> Optional[str]:
    """Radix-prefix affinity key for a generation request: the head of
    the prompt (or the first chat message). Multi-turn conversations
    share their prompt head verbatim — the system prompt / first turn —
    so hashing it routes every turn of a session to the SAME decode
    replica, whose radix tree still holds the session's prefix pages.
    64 chars is plenty to separate sessions and cheap to hash; requests
    without a recognizable prompt get no affinity (pure load placement).
    """
    if not body or not path.startswith(("/v1/completions",
                                        "/v1/chat/completions")):
        return None
    try:
        req = json.loads(body)
    except ValueError:
        return None
    head = ""
    if isinstance(req.get("prompt"), str):
        head = req["prompt"]
    # "messages" is the CLIENT-authored OpenAI chat field; no in-repo
    # writer produces request bodies.
    # lint: disable=X705
    elif isinstance(req.get("messages"), list) and req["messages"]:
        first = req["messages"][0]
        if isinstance(first, dict):
            head = str(first.get("content") or "")
    return head[:64] or None


def quiet_handle_error(httpd) -> None:
    """Replace socketserver's print-a-traceback error hook on ``httpd``:
    connection breakage (a client hanging up mid-response) is ROUTINE under
    load shedding and chaos testing, not a bug worth a stderr traceback.
    Anything else still prints."""

    def handle_error(request, client_address):
        exc = sys.exc_info()[1]
        if isinstance(exc, (ConnectionError, TimeoutError)):
            return
        traceback.print_exc()

    httpd.handle_error = handle_error

#: Local (non-proxied) router endpoints. The spans-export path is owned
#: by obs/fleet.py (the collector registers it as a drain source).
ROUTER_METRICS_PATH = "/-/router/metrics"
ROUTER_TRACES_PATH = "/-/router/debug/traces"
ROUTER_SPANS_PATH = ROUTER_SPANS_EXPORT_PATH


class Router:
    """Weighted HTTP proxy over predictor replicas.

    Backends are registered per traffic group (e.g. "latest"/"previous"
    during a canary rollout), each group with a weight percent; requests
    pick a group by weight, then round-robin inside it."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 queue_timeout: float = 120.0, *,
                 upstream_timeout: float = 600.0,
                 eject_threshold: int = 3,
                 eject_period: float = 5.0,
                 max_retries: int = 2):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._groups: dict[str, list[str]] = {}    # guarded_by: _lock
        self._weights: dict[str, int] = {}         # guarded_by: _lock
        self._rr = itertools.count()    # lockfree: next() is GIL-atomic
        self._pending = 0               # guarded_by: _lock
        self._last_activity = 0.0   # guarded_by: _lock (monotonic stamp)
        self._closed = False            # guarded_by: _lock
        self.queue_timeout = queue_timeout
        self.upstream_timeout = upstream_timeout
        self.eject_threshold = max(1, int(eject_threshold))
        self.eject_period = eject_period
        self.max_retries = max(0, int(max_retries))
        # outlier-ejection state (all under self._lock)
        self._fails: dict[str, int] = {}           # guarded_by: _lock
        self._ejected_until: dict[str, float] = {}  # guarded_by: _lock
        self._draining: set[str] = set()            # guarded_by: _lock
        # ``panic_total``/``probe_total`` mirror panic_picks/
        # half_open_probes under the stable metric names the autoscaler
        # post-mortems key on (kftpu_router_panic_total distinguishes
        # "backends ejected" from "backends slow" — see ISSUE 6).
        self.stats = {"picks": 0, "retries": 0,    # guarded_by: _lock
                      "connect_failures": 0,
                      "http_5xx": 0, "ejections": 0, "half_open_probes": 0,
                      "panic_picks": 0, "panic_total": 0, "probe_total": 0,
                      "queue_timeouts": 0,
                      "deadline_exhausted": 0,
                      "disagg_picks": 0, "disagg_fallbacks": 0,
                      "affinity_hits": 0, "affinity_misses": 0}
        # Disaggregated fleet mode (set_pools): role -> backend urls,
        # plus the freshest scraped placement signals per backend.
        self._pools: dict[str, list[str]] = {}     # guarded_by: _lock
        self._signals: dict[str, dict] = {}        # guarded_by: _lock
        # Scrape-origin health: a pool member that stops answering its
        # /metrics scrape gets ejected even though it takes no proxied
        # traffic (a dead DECODE backend would otherwise be picked
        # forever, costing every request a failed handoff + recompute).
        # Kept separate from the request-failure counter so a healthy
        # scrape can never launder real traffic failures.
        self._scrape_fails: dict[str, int] = {}    # guarded_by: _lock
        # Optional history-backed signal source (obs/fleet.py): maps a
        # backend url to its newest exposition text, replacing the HTTP
        # fetch when set. lockfree: assigned once at wiring time.
        self._metrics_source: Optional[Callable[[str], Optional[str]]] = None
        self.scrape_interval = 0.25
        self._scrape_stop = threading.Event()
        self._scrape_thread: Optional[threading.Thread] = None
        self.httpd = ThreadingHTTPServer((host, port), _make_handler(self))
        self.httpd.daemon_threads = True
        quiet_handle_error(self.httpd)
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def set_backends(self, groups: dict[str, list[str]],
                     weights: Optional[dict[str, int]] = None) -> None:
        with self._cond:
            self._groups = {g: list(urls) for g, urls in groups.items() if urls}
            if weights:
                self._weights = dict(weights)
            else:
                self._weights = {g: 100 // max(len(self._groups), 1)
                                 for g in self._groups}
            # Prune per-backend state for urls that left the rotation —
            # ports get reused, and a recycled port must not inherit its
            # predecessor's failure history.
            live = {u for urls in self._groups.values() for u in urls}
            for d in (self._fails, self._ejected_until, self._scrape_fails):
                for u in [u for u in d if u not in live]:
                    d.pop(u)
            self._draining &= live
            if self._groups:
                self._cond.notify_all()   # wake cold-start queued requests

    # -- disaggregated pools (token-aware placement) ------------------------

    def set_pools(self, pools: dict[str, list[str]], *,
                  scrape: bool = True) -> None:
        """Register role-specialized backend pools (``prefill`` /
        ``decode`` / ``unified``). All pool members join the regular
        rotation (so ejection, draining, panic routing and scale-from-
        zero parking keep working unchanged); placement then routes
        every request through ``pick_disaggregated`` on the scraped
        token signals. An empty dict leaves fleet mode."""
        union: list[str] = []
        for urls in pools.values():
            for u in urls:
                if u not in union:
                    union.append(u)
        self.set_backends({"latest": union} if union else {})
        with self._lock:
            self._pools = {r: list(urls) for r, urls in pools.items()
                           if urls}
            live = set(union)
            for u in [u for u in self._signals if u not in live]:
                self._signals.pop(u)
        if scrape and union:
            self.start_signal_scrape()

    @property
    def has_pools(self) -> bool:
        with self._lock:
            return bool(self._pools)

    def note_signals(self, url: str, signals: dict) -> None:
        """Feed one backend's placement signals (the scrape loop's
        writer; tests and controllers may inject directly)."""
        with self._lock:
            self._signals[url] = dict(signals)

    def set_metrics_source(self, source: Optional[
            Callable[[str], Optional[str]]]) -> None:
        """Install a history-backed signal source: ``source(url)``
        returns the backend's newest ``/metrics`` exposition text (e.g.
        ``MetricsHistory.latest_text``) or None to fall back to a live
        HTTP fetch. The scrape loop's PARSE and placement fold are
        unchanged — only where the bytes come from moves, so routing
        decisions on steady traffic are identical either way."""
        self._metrics_source = source

    def start_signal_scrape(self) -> None:
        if self._scrape_thread is not None and \
                self._scrape_thread.is_alive():
            return
        self._scrape_stop.clear()
        self._scrape_thread = threading.Thread(
            target=self._scrape_loop, daemon=True, name="router-scrape")
        self._scrape_thread.start()

    def _scrape_loop(self) -> None:
        while not self._scrape_stop.wait(self.scrape_interval):
            self.scrape_signals()

    def scrape_signals(self) -> None:
        """One pass over every pooled backend's /metrics exposition
        (the same grammar the SLO autoscaler scrapes through). An
        unreachable backend keeps its last-known signals — ejection,
        not staleness, is what removes it from placement."""
        with self._lock:
            urls = [u for urls in self._pools.values() for u in urls]
        for url in dict.fromkeys(urls):
            if self._metrics_source is not None:
                text = self._metrics_source(url)
                if text is not None:
                    sig = self._parse_signals(text)
                    if sig is not None:
                        self.note_signals(url, sig)
                    continue
                # History has nothing for this backend (yet): fall
                # through to the live fetch below.

            def _fetch(_attempt, url=url):
                with urllib.request.urlopen(url + "/metrics",
                                            timeout=1.0) as r:
                    return r.read().decode()

            try:
                # Shared backoff policy (serve/retry.py): one transient
                # scrape hiccup must not advance a backend toward
                # scrape-origin ejection.
                text = call_with_retry(_fetch, policy=PROBE_POLICY)
            except OSError:
                with self._lock:
                    self._scrape_fails[url] = \
                        self._scrape_fails.get(url, 0) + 1
                    if self._scrape_fails[url] >= self.eject_threshold:
                        now = time.monotonic()
                        if self._ejected_until.get(url, 0.0) <= now:
                            self.stats["ejections"] += 1
                        self._ejected_until[url] = now + self.eject_period
                continue
            with self._lock:
                self._scrape_fails.pop(url, None)
            sig = self._parse_signals(text)
            if sig is not None:
                self.note_signals(url, sig)

    @staticmethod
    def _parse_signals(text: str) -> Optional[dict]:
        out = {"pending_prefill_tokens": 0.0, "kv_pages_resident": 0.0,
               "kv_pages_cached": 0.0, "kv_pages_remote": 0.0,
               "in_flight": 0.0, "adapters": frozenset()}
        adapters: set[str] = set()
        try:
            samples = parse_exposition(text)
        except ValueError:
            return None
        for name, _labels, value in samples:
            if name in ROUTER_SCRAPE_SERIES:
                # Contract audit: the router CONSUMED this series
                # (no-op unless KFTPU_SANITIZE=contract).
                contract_note_series(name, "consumed")
            if name == "kftpu_engine_pending_prefill_tokens":
                out["pending_prefill_tokens"] += value
            elif name == "kftpu_engine_kv_pages_resident":
                out["kv_pages_resident"] += value
            elif name == "kftpu_engine_kv_pages_cached":
                out["kv_pages_cached"] += value
            elif name == "kftpu_engine_kv_pages_remote":
                out["kv_pages_remote"] += value
            elif name == "kftpu_engine_adapters_resident":
                # Which LoRA adapters are HOT on this backend: the
                # model-id routing signal (one adapter-labeled sample
                # per resident adapter; the 0 sample has no label).
                a = _labels.get("adapter")
                if a and value > 0:
                    adapters.add(a)
            elif name == "kftpu_serving_in_flight":
                out["in_flight"] += value
        out["adapters"] = frozenset(adapters)
        return out

    def _healthy_locked(self, urls, exclude: frozenset,
                        now: float) -> list[str]:
        return [u for u in urls
                if u not in exclude and u not in self._draining
                and self._ejected_until.get(u, 0.0) <= now]

    def pick_disaggregated(self, exclude: frozenset = frozenset(), *,
                           affinity: Optional[str] = None
                           ) -> tuple[Optional[str], Optional[str]]:
        """Token-aware placement: ``(backend, decode_target)``.

        Healthy prefill AND decode pools → the least-pending-prefill-
        tokens prefill backend carries the request, stamped with the
        least-resident-KV-pages decode backend for its handoff. An
        ``affinity`` key (the request's prompt head) overrides the
        load-based decode pick with its rendezvous-hash preferred
        replica WHEN that replica is healthy — every turn of a session
        lands where the session's radix prefix is warm — and falls
        through to load placement (``affinity_misses``) when it is not:
        affinity is a cache hint, never a health exemption. A pool
        with no healthy member → unified fallback: any healthy backend
        (unified first, then decode, then prefill — every role serves a
        whole request locally), no handoff header. Everything ejected →
        panic-route like the classic picker. ``(None, None)`` = nothing
        at all to try."""
        now = time.monotonic()
        with self._lock:
            rot = next(self._rr)
            prefills = self._healthy_locked(
                self._pools.get("prefill", ()), exclude, now)
            decodes = self._healthy_locked(
                self._pools.get("decode", ()), exclude, now)
            if prefills and decodes:
                def sig(u):
                    return self._signals.get(u, {})

                # Rotate before min: equal signals round-robin instead
                # of pinning one backend (min is stable).
                prefills = prefills[rot % len(prefills):] \
                    + prefills[:rot % len(prefills)]
                decodes = decodes[rot % len(decodes):] \
                    + decodes[:rot % len(decodes)]
                p = min(prefills,
                        key=lambda u: (sig(u).get("pending_prefill_tokens",
                                                  0.0),
                                       sig(u).get("in_flight", 0.0)))
                # Referenced pages are load; cached pages are an asset
                # (more cached prefix content = better hit odds), so
                # among equally-loaded decode backends prefer the
                # warmer cache (negated in the ascending-min key).
                d = min(decodes,
                        key=lambda u: (sig(u).get("kv_pages_resident", 0.0),
                                       sig(u).get("in_flight", 0.0),
                                       -sig(u).get("kv_pages_cached", 0.0)))
                if affinity:
                    # The preferred replica is computed over the WHOLE
                    # decode pool (not just the healthy slice): a key
                    # must keep preferring its home replica through a
                    # transient ejection, so a miss here means "home is
                    # down, go cold elsewhere", not a silent remap.
                    pool = self._pools.get("decode", ())
                    home = max(pool, key=lambda u: _rendezvous(
                        affinity, u)) if pool else None
                    if home is not None and home in decodes:
                        d = home
                        self.stats["affinity_hits"] += 1
                    else:
                        self.stats["affinity_misses"] += 1
                self.stats["disagg_picks"] += 1
                return p, d
            for pool in ("unified", "decode", "prefill"):
                ok = self._healthy_locked(self._pools.get(pool, ()),
                                          exclude, now)
                if ok:
                    self.stats["disagg_fallbacks"] += 1
                    return ok[rot % len(ok)], None
            suspects = [u for urls in self._pools.values() for u in urls
                        if u not in exclude and u not in self._draining]
            if suspects:
                self.stats["panic_picks"] += 1
                self.stats["panic_total"] += 1
                return min(suspects,
                           key=lambda u: self._ejected_until.get(u, 0.0)), \
                    None
            return None, None

    def decode_alternates(self, primary: Optional[str],
                          exclude: frozenset = frozenset(), *,
                          n: int = 2) -> tuple[str, ...]:
        """Up to ``n`` healthy decode-pool members besides ``primary`` —
        the prefill replica's retry ladder (``X-Kftpu-Decode-Alts``):
        when its handoff to the primary decode target fails it retries
        against these, in order, before degrading to local recompute.
        Stamped by the router because only the router knows pool health;
        the prefill replica never guesses at fleet membership."""
        now = time.monotonic()
        with self._lock:
            ok = self._healthy_locked(self._pools.get("decode", ()),
                                      exclude, now)
        return tuple(u for u in ok if u != primary)[:n]

    # -- outlier ejection / draining ----------------------------------------

    def note_backend_failure(self, url: str, *, connect: bool = False) -> None:
        """One failed request against ``url`` (connect failure or 5xx).
        ``eject_threshold`` consecutive failures eject it for
        ``eject_period`` seconds."""
        with self._lock:
            self._fails[url] = self._fails.get(url, 0) + 1
            self.stats["connect_failures" if connect else "http_5xx"] += 1
            if self._fails[url] >= self.eject_threshold:
                self._ejected_until[url] = time.monotonic() + self.eject_period
                self.stats["ejections"] += 1

    def note_backend_success(self, url: str) -> None:
        with self._lock:
            self._fails.pop(url, None)
            self._ejected_until.pop(url, None)

    def set_draining(self, url: str, draining: bool = True) -> None:
        """Mark a backend draining: new requests never pick it; in-flight
        requests (already connected) finish undisturbed."""
        with self._lock:
            if draining:
                self._draining.add(url)
            else:
                self._draining.discard(url)

    def count(self, stat: str, n: int = 1) -> None:
        with self._lock:
            self.stats[stat] = self.stats.get(stat, 0) + n

    def snapshot(self) -> dict:
        now = time.monotonic()
        with self._lock:
            return dict(self.stats, pending=self._pending,
                        backends=sum(len(u) for u in self._groups.values()),
                        ejected=sum(1 for t in self._ejected_until.values()
                                    if t > now),
                        draining=len(self._draining))

    @property
    def pending(self) -> int:
        """Requests parked waiting for a backend (the activation signal)."""
        with self._lock:
            return self._pending

    @property
    def last_activity(self) -> float:
        """Monotonic timestamp of the most recent request arrival or
        completion through this router. The KPA-analog idle clock counts
        from here — from *traffic*, not from scale events — so a replica
        that just answered a request (however slow the cold start was) is
        guaranteed a full quiet cooldown before it can be culled."""
        with self._lock:
            return self._last_activity

    def note_activity(self) -> None:
        with self._lock:
            self._last_activity = time.monotonic()

    # -- backend selection ---------------------------------------------------

    def _eligible_locked(self, exclude: frozenset,
                         now: float) -> dict[str, list[str]]:
        out: dict[str, list[str]] = {}
        for g, urls in self._groups.items():
            ok = [u for u in urls
                  if u not in exclude and u not in self._draining
                  and self._ejected_until.get(u, 0.0) <= now]
            if ok:
                out[g] = ok
        return out

    def _pick_locked(self, exclude: frozenset = frozenset(),
                     model: Optional[str] = None) -> Optional[str]:
        now = time.monotonic()
        eligible = self._eligible_locked(exclude, now)
        if not eligible:
            # Panic routing (Envoy panic-threshold analog): every backend is
            # ejected — try the least-recently-ejected suspect rather than
            # park the request into a guaranteed queue timeout.
            suspects = [u for urls in self._groups.values() for u in urls
                        if u not in exclude and u not in self._draining]
            if not suspects:
                return None
            self.stats["panic_picks"] += 1
            self.stats["panic_total"] += 1
            return min(suspects,
                       key=lambda u: self._ejected_until.get(u, 0.0))
        groups = [(g, self._weights.get(g, 0)) for g in eligible]
        total = sum(w for _, w in groups) or len(groups)
        r = random.uniform(0, total)
        acc = 0.0
        chosen = groups[-1][0]
        for g, w in groups:
            acc += w if total else 1
            if r <= acc:
                chosen = g
                break
        urls = eligible[chosen]
        if model is not None:
            # Model-id routing (multi-tenant LoRA): prefer a backend
            # that already has the adapter HOT — a cold pick pays a
            # hot-load (and possibly an eviction) before its prefill.
            # Falls back to the whole rotation when nobody has it (the
            # pick itself warms that backend). Round-robin WITHIN the
            # warm set keeps one popular adapter from pinning a single
            # replica.
            warm = [u for u in urls
                    if model in self._signals.get(u, {}).get(
                        "adapters", ())]
            if warm:
                urls = warm
        url = urls[next(self._rr) % len(urls)]
        if url in self._ejected_until:
            # Expired ejection window: this pick IS the half-open probe.
            # Re-arm the window so concurrent traffic keeps avoiding the
            # backend until the probe's verdict (success clears the state,
            # failure re-ejects).
            self._ejected_until[url] = now + self.eject_period
            self.stats["half_open_probes"] += 1
            self.stats["probe_total"] += 1
        return url

    def pick(self, exclude: frozenset = frozenset(),
             model: Optional[str] = None) -> Optional[str]:
        with self._lock:
            return self._pick_locked(exclude, model=model)

    def pick_or_wait(self, timeout: Optional[float] = None,
                     exclude: frozenset = frozenset(),
                     model: Optional[str] = None) -> Optional[str]:
        """Pick a backend, queueing until one registers (scale-from-zero
        path). Returns None only after ``timeout`` (default: the router's
        queue_timeout) with still no backend."""
        deadline = time.monotonic() + (
            timeout if timeout is not None else self.queue_timeout)
        with self._cond:
            backend = self._pick_locked(exclude, model=model)
            if backend is not None:
                return backend
            self._pending += 1
            try:
                while not self._closed:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                    self._cond.wait(remaining)
                    backend = self._pick_locked(exclude, model=model)
                    if backend is not None:
                        return backend
                return None   # router torn down: fail fast, don't hold 120s
            finally:
                self._pending -= 1

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def start(self) -> None:
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True, name="router")
        self._thread.start()

    def stop(self) -> None:
        from kubeflow_tpu.runtime.sanitize import assert_threads_quiescent

        self._scrape_stop.set()
        if self._scrape_thread is not None:
            self._scrape_thread.join(timeout=5.0)
            self._scrape_thread = None
        with self._cond:
            self._closed = True
            self._cond.notify_all()   # release every parked request
        self.httpd.shutdown()
        self.httpd.server_close()
        httpd_thread = self._thread
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        # KFTPU_SANITIZE=threads: the scrape loop binds to this router
        # (owner identity); the serve thread binds to httpd, so it is
        # audited explicitly. No-op when the mode is off.
        assert_threads_quiescent(owner=self, grace_s=5.0)
        if httpd_thread is not None:
            assert_threads_quiescent(threads=(httpd_thread,), grace_s=5.0)


def _make_handler(router: Router):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *args) -> None:
            pass

        def _send(self, code: int, data: bytes,
                  ctype: str = "application/json") -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _error(self, code: int, message: str) -> None:
            self._send(code, f'{{"error": "{message}"}}'.encode())

        def _router_metrics(self) -> None:
            snap = router.snapshot()
            reg = MetricsRegistry()
            for k, v in sorted(snap.items()):
                reg.gauge(f"kftpu_router_{k}").set(v)
            self._send(200, reg.render().encode(), ctype="text/plain")

        def _proxy(self) -> None:
            if self.path == ROUTER_METRICS_PATH:
                # Observability scrape, not traffic: must not feed the
                # KPA-analog activity clock (a 1 s scrape loop would pin
                # the service out of scale-to-zero forever).
                return self._router_metrics()
            if self.path.split("?", 1)[0] == ROUTER_TRACES_PATH:
                return self._send(
                    200, json.dumps(debug_traces_payload(self.path),
                                    default=str).encode())
            if self.path.split("?", 1)[0] == ROUTER_SPANS_PATH:
                # Fleet-trace drain (obs/fleet.py) — observability, not
                # traffic: must not feed the KPA activity clock either.
                return self._send(
                    200, json.dumps(spans_export_payload(process="router"),
                                    default=str).encode())
            router.note_activity()
            try:
                self._proxy_inner()
            finally:
                # Stamp at COMPLETION too: a request slower than the idle
                # cooldown (e.g. a cold start that had to spawn + compile)
                # must restart the clock when it answers, or the replica
                # gets culled the moment in_flight drops back to zero.
                router.note_activity()

        def _budget_s(self) -> float:
            """Remaining client budget (seconds): deadline header if the
            client sent one, capped by the router's upstream timeout."""
            budget = router.upstream_timeout
            hdr = self.headers.get(DEADLINE_HEADER)
            contract_note_header(DEADLINE_HEADER, direction="read")
            if hdr:
                try:
                    budget = min(budget, max(float(hdr) / 1e3, 0.0))
                except ValueError:
                    pass
            return budget

        def _proxy_inner(self) -> None:
            # Trace root (or join, when the client already carries a
            # context): every hop below — backend pick, upstream request,
            # response relay — is annotated on this span, and the context
            # rides the X-Kftpu-Trace header so the model server and the
            # engine scheduler continue the SAME trace id.
            tracer = get_tracer()
            contract_note_header(TRACE_HEADER, direction="read")
            with tracer.span(
                    "router.request",
                    parent=tracer.extract(self.headers.get(TRACE_HEADER)),
                    path=self.path) as sp:
                self._proxy_upstream(sp)

        def _proxy_upstream(self, sp) -> None:
            deadline = time.monotonic() + self._budget_s()
            n = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(n) if n else None
            # Model-id routing key (multi-tenant LoRA): requests naming
            # a model prefer backends already serving it hot.
            contract_note_header(MODEL_HEADER, direction="read")
            model_id = (self.headers.get(MODEL_HEADER) or "").strip() \
                or None
            tried: set[str] = set()
            first_attempt = True
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    router.count("deadline_exhausted")
                    sp.set_attrs(code=504)
                    return self._error(504, "deadline exhausted in router")
                decode_target = None
                if router.has_pools:
                    # Disaggregated fleet: token-aware placement decides
                    # BOTH hops here — the prefill backend that carries
                    # the request and the decode backend its KV hands
                    # off to (stamped on the forwarded request below).
                    # The prompt head rides along as the prefix-affinity
                    # key so a session's turns chase their warm replica.
                    backend, decode_target = router.pick_disaggregated(
                        exclude=frozenset(tried),
                        affinity=_affinity_key(self.path, body))
                elif first_attempt:
                    # Only the first pick parks (scale-from-zero): a retry
                    # already had a live-looking rotation moments ago, so a
                    # blocking wait would just burn the client's budget.
                    backend = router.pick_or_wait(
                        timeout=min(remaining, router.queue_timeout),
                        exclude=frozenset(tried), model=model_id)
                else:
                    backend = router.pick(exclude=frozenset(tried),
                                          model=model_id)
                if backend is None:
                    if tried:
                        # Retried through the whole rotation: every backend
                        # refused the connection — a backend-side outage,
                        # not a routing/queue condition.
                        sp.set_attrs(code=502)
                        return self._error(
                            502, "backend unreachable: all backends failed")
                    router.count("queue_timeouts")
                    sp.set_attrs(code=503)
                    return self._error(
                        503, "no ready backends (queue timeout)")
                router.count("picks")
                sp.set_attrs(backend=backend)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    router.count("deadline_exhausted")
                    sp.set_attrs(code=504)
                    return self._error(504, "deadline exhausted in router")
                fwd_headers = {
                    "Content-Type": self.headers.get(
                        "Content-Type", "application/json"),
                    # Forward the REMAINING budget: the replica stamps
                    # the engine-side request deadline from it.
                    DEADLINE_HEADER: str(int(remaining * 1e3)),
                }
                if self.headers.get(QOS_HEADER):
                    # QoS class rides to the replica verbatim — the
                    # engine scheduler enforces the class policy.
                    contract_note_header(QOS_HEADER, direction="read")
                    fwd_headers[QOS_HEADER] = self.headers[QOS_HEADER]
                if decode_target:
                    # Handoff placement: the prefill replica POSTs its
                    # KV to exactly this decode-pool member — and the
                    # alternates ladder it may retry against when that
                    # member dies between this pick and the handoff.
                    fwd_headers[DECODE_BACKEND_HEADER] = decode_target
                    alts = router.decode_alternates(
                        decode_target, frozenset(tried))
                    if alts:
                        fwd_headers[DECODE_ALTS_HEADER] = ",".join(alts)
                if model_id:
                    # The replica resolves the model id itself (adapter
                    # hot-load on miss, 404 on unknown).
                    fwd_headers[MODEL_HEADER] = model_id
                trace_hdr = get_tracer().inject(sp)
                if trace_hdr:
                    fwd_headers[TRACE_HEADER] = trace_hdr
                # Contract audit (KFTPU_SANITIZE=contract): record which
                # X-Kftpu-* headers actually ride this hop; no-op when off.
                for h in fwd_headers:
                    if h.startswith("X-Kftpu"):
                        contract_note_header(h, direction="set")
                req = urllib.request.Request(
                    backend + self.path, data=body, method=self.command,
                    headers=fwd_headers)
                try:
                    resp = urllib.request.urlopen(req, timeout=remaining)
                except urllib.error.HTTPError as exc:
                    # A response arrived: forward it verbatim. 5xx counts
                    # toward outlier ejection (the Envoy consecutive-5xx
                    # rule) but is NOT retried — the backend consumed the
                    # request, and generation is not idempotent.
                    if exc.code >= 500:
                        router.note_backend_failure(backend)
                    else:
                        router.note_backend_success(backend)
                    sp.set_attrs(code=exc.code)
                    data = exc.read()
                    self._send(exc.code, data, ctype=exc.headers.get(
                        "Content-Type", "application/json"))
                    return
                except OSError as exc:
                    # Connection-level failure before any response byte:
                    # nothing reached a model, nothing reached the client —
                    # the ONE case where a retry on a different backend is
                    # unconditionally safe.
                    router.note_backend_failure(backend, connect=True)
                    sp.add_event("connect_failure", backend=backend)
                    tried.add(backend)
                    first_attempt = False
                    if len(tried) <= router.max_retries:
                        router.count("retries")
                        continue
                    sp.set_attrs(code=502)
                    return self._error(502, f"backend unreachable: {exc}")
                def read_upstream(*args):
                    # Mid-response read failures are the BACKEND's fault
                    # (it died streaming) — distinct from a client hang-up
                    # on the write side, which must not eject a healthy
                    # backend.
                    try:
                        return resp.read(*args)
                    except OSError:
                        router.note_backend_failure(backend)
                        raise

                sp.set_attrs(code=resp.status)
                try:
                    with resp:
                        self.send_response(resp.status)
                        ctype = resp.headers.get("Content-Type",
                                                 "application/json")
                        self.send_header("Content-Type", ctype)
                        if "event-stream" in ctype:
                            self.send_header("Transfer-Encoding", "chunked")
                            self.end_headers()
                            while True:
                                piece = read_upstream(512)
                                if not piece:
                                    break
                                self.wfile.write(
                                    f"{len(piece):x}\r\n".encode()
                                    + piece + b"\r\n")
                                self.wfile.flush()
                            self.wfile.write(b"0\r\n\r\n")
                        else:
                            data = read_upstream()
                            self.send_header("Content-Length",
                                             str(len(data)))
                            self.end_headers()
                            self.wfile.write(data)
                except OSError:
                    # Response bytes may already be on the wire, so no
                    # retry — close the connection, which is the explicit
                    # error a streaming client can detect.
                    self.close_connection = True
                    return
                router.note_backend_success(backend)
                return

        do_GET = _proxy
        do_POST = _proxy

    return Handler
