"""Multi-tenant LoRA serving: adapter registry + batched multi-adapter
dispatch math (ROADMAP item 4 — the more-MODELS-per-chip axis).

Thousands of fine-tuned variants cannot mean thousands of engines: one
engine serves N rank-r LoRA adapters over ONE set of shared base
weights (the S-LoRA/Punica motif, TPU-native). The pieces:

- **Packed adapter buffers.** Every loaded adapter occupies one SLOT of
  a packed device buffer per target projection: ``A [L, S, d_in, r]``
  and ``B [L, S, r, d_out]`` (S = ``LoRASpec.max_adapters`` slots, r =
  the spec's rank cap — lower-rank adapters zero-pad, which leaves
  ``A@B`` exact). The buffers ride into every dispatch whole, so the
  trace set is FIXED regardless of which adapters are hot: adapter
  churn swaps slot contents through a donated scatter, never shapes —
  the packed buffer IS the pow2 pad of the active-adapter set, and the
  recompile sanitizer sees zero steady-state retraces across churn.
- **Batched multi-adapter dispatch.** Each engine slot carries an
  ``adapter_idx`` (device-resident, serve/device_state.py); the decode
  and prefill dispatches gather each row's slices and apply the
  low-rank update as one gather + two einsums per target
  (``lora_contrib``). ``adapter_idx = -1`` multiplies the delta by an
  exact 0.0, so base-traffic rows are bit-identical to a LoRA-free
  engine — one compiled program serves every base/adapter mix.
- **Hot-load / evict.** The registry LRU-loads adapters into slots on
  demand (weights from the artifact store or an in-process source) and
  evicts only ref-0 adapters; every reference is owner-stamped so
  ``KFTPU_SANITIZE=refcount`` names leakers and ``assert_quiescent``
  stays exact per owner — the same discipline as the page allocator.

Correctness contract: greedy decode under every loaded adapter is
token-identical to a single-model engine running the MERGED weights
(``merged_params``), dense and paged (tests/test_serve_lora.py), and
prefix-cache KV is namespaced per adapter (engine._kv_match) so two
tenants sharing a prompt never share each other's KV.
"""

from __future__ import annotations

import dataclasses
import io
import logging
import threading
from collections import OrderedDict
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.models.config import DecoderConfig
from kubeflow_tpu.models.decoder import Params
# The traced per-row low-rank math and the scan-threading helpers live
# with the model layers (the prefill forward applies them there);
# re-exported here so engine/paged code imports one LoRA surface.
from kubeflow_tpu.models.layers import (  # noqa: F401
    apply_lora_layer, index_layer, layer_view, lora_contrib, slice_layers,
)

logger = logging.getLogger("kubeflow_tpu.serve.lora")

#: Attention projections LoRA may target, with (d_in, d_out) factories.
LORA_TARGETS = ("wq", "wk", "wv", "wo")


class AdapterSlotsExhausted(Exception):
    """Every adapter slot is referenced by a live request: the arrival
    cannot hot-load until one releases. The engine treats this as
    admission backpressure (requeue, not failure) — exactly the page
    allocator's exhaustion discipline."""


def target_dims(cfg: DecoderConfig, target: str) -> tuple[int, int]:
    """(d_in, d_out) of one attention projection's LoRA factors."""
    d = cfg.hidden
    if target == "wq":
        return d, cfg.n_heads * cfg.head_dim
    if target == "wk" or target == "wv":
        return d, cfg.n_kv_heads * cfg.head_dim
    if target == "wo":
        return cfg.n_heads * cfg.head_dim, d
    raise ValueError(f"unknown LoRA target {target!r}; one of {LORA_TARGETS}")


@dataclasses.dataclass
class AdapterSpec:
    """One registered adapter. ``weights`` maps target -> (A [L, d_in, r],
    B [L, r, d_out]) numpy/JAX arrays; ``source`` is a lazy alternative
    (called once, at hot-load — the artifact-store pull path). ``alpha``
    scales the delta as alpha/rank (the classic LoRA scaling)."""

    name: str
    rank: int
    alpha: float = 16.0
    weights: Optional[dict[str, tuple]] = None
    source: Optional[Callable[[], dict[str, tuple]]] = None

    @property
    def scale(self) -> float:
        return self.alpha / max(self.rank, 1)

    def resolve_weights(self) -> dict[str, tuple]:
        if self.weights is not None:
            return self.weights
        if self.source is None:
            raise ValueError(f"adapter {self.name!r} has no weights/source")
        w = self.source()
        return w


def init_adapter_weights(key: jax.Array, cfg: DecoderConfig, rank: int,
                         targets: Sequence[str] = ("wq", "wv"),
                         scale: float = 0.5) -> dict[str, tuple]:
    """Random nonzero A/B factors (synthetic fine-tunes for tests and
    loadgen). Real LoRA training initializes B to zero; a SERVED adapter
    has trained nonzero B — a zero-delta adapter would make every
    token-identity assertion vacuously true, so both factors draw."""
    out: dict[str, tuple] = {}
    for t in targets:
        din, dout = target_dims(cfg, t)
        key, ka, kb = jax.random.split(key, 3)
        a = jax.random.normal(ka, (cfg.n_layers, din, rank),
                              jnp.float32) * (scale / np.sqrt(din))
        b = jax.random.normal(kb, (cfg.n_layers, rank, dout),
                              jnp.float32) * (scale / np.sqrt(rank))
        out[t] = (np.asarray(a), np.asarray(b))
    return out


def adapter_delta(weights: dict[str, tuple], target: str,
                  scale: float) -> Optional[np.ndarray]:
    """Dense [L, d_in, d_out] delta of one target (None if untargeted)."""
    ab = weights.get(target)
    if ab is None:
        return None
    a, b = np.asarray(ab[0]), np.asarray(ab[1])
    return np.einsum("ldr,lro->ldo", a, b) * scale


def merged_params(params: Params, cfg: DecoderConfig,
                  spec: AdapterSpec) -> Params:
    """Base params with ``spec``'s delta FOLDED into the attention
    weights — the single-model reference the multi-adapter dispatch must
    be token-identical to (the acceptance-criteria oracle). Handles both
    the scanned ([L, ...]-stacked) and list-of-blocks layer layouts."""
    weights = spec.resolve_weights()
    out = jax.tree.map(lambda x: x, params)          # fresh containers

    def merge_attn(attn: dict, layer: Optional[int]) -> dict:
        attn = dict(attn)
        for t in LORA_TARGETS:
            delta = adapter_delta(weights, t, spec.scale)
            if delta is None:
                continue
            if layer is not None:
                delta = delta[layer]
            w = np.asarray(attn[t], np.float32)
            attn[t] = jnp.asarray(w + delta.reshape(w.shape),
                                  attn[t].dtype)
        return attn

    layers = out["layers"]
    if isinstance(layers, list):
        out["layers"] = [
            {**blk, "attn": merge_attn(blk["attn"], i)}
            for i, blk in enumerate(layers)]
    else:
        layers = dict(layers)
        layers["attn"] = merge_attn(layers["attn"], None)
        out["layers"] = layers
    return out


# -- artifact-store round trip -------------------------------------------------

def adapter_to_bytes(weights: dict[str, tuple], *, rank: int,
                     alpha: float) -> bytes:
    """Serialize adapter factors as an npz blob (the artifact-store
    payload: ``store.put_bytes`` + ``store.register`` publishes it;
    ``adapter_spec_from_store`` pulls it back lazily at hot-load)."""
    arrs: dict[str, np.ndarray] = {
        "__meta_rank": np.asarray([rank], np.int32),
        "__meta_alpha": np.asarray([alpha], np.float32),
    }
    for t, (a, b) in weights.items():
        arrs[f"{t}.a"] = np.asarray(a)
        arrs[f"{t}.b"] = np.asarray(b)
    buf = io.BytesIO()
    np.savez(buf, **arrs)
    return buf.getvalue()


def adapter_from_bytes(name: str, blob: bytes) -> AdapterSpec:
    with np.load(io.BytesIO(blob)) as z:
        rank = int(z["__meta_rank"][0])
        alpha = float(z["__meta_alpha"][0])
        weights: dict[str, tuple] = {}
        for key in z.files:
            if key.endswith(".a"):
                t = key[:-2]
                weights[t] = (z[f"{t}.a"], z[f"{t}.b"])
    return AdapterSpec(name=name, rank=rank, alpha=alpha, weights=weights)


def adapter_spec_from_store(store, name: str, uri: str, *, rank: int,
                            alpha: float = 16.0) -> AdapterSpec:
    """Registry entry whose weights pull from the platform artifact
    store at HOT-LOAD time (not registration) — registering a thousand
    tenants costs a thousand dict entries, not a thousand uploads."""

    def source() -> dict[str, tuple]:
        spec = adapter_from_bytes(name, store.get_bytes(store.resolve(uri)))
        return spec.resolve_weights()

    return AdapterSpec(name=name, rank=rank, alpha=alpha, source=source)


# -- the registry --------------------------------------------------------------

def _upload_slot(buffers: dict, slot, scale, updates: dict) -> dict:  # traced
    """Scatter one adapter's padded factors into its packed slot
    (donated in/out — a hot-load swaps slot contents, never shapes)."""
    out = dict(buffers)
    out["scale"] = buffers["scale"].at[slot].set(scale)
    tgt = dict(buffers["targets"])
    for t, (a, b) in updates.items():
        pa, pb = tgt[t]
        tgt[t] = (pa.at[:, slot].set(a), pb.at[:, slot].set(b))
    out["targets"] = tgt
    return out


class AdapterRegistry:
    """Registered adapters + the packed device buffers their hot slots
    live in.

    Thread contract: ``register``/``known``/``names`` are thread-safe
    (the model server's submit path checks membership from handler
    threads); slot state, refcounts and the device buffers are
    SCHEDULER-CONFINED like the page allocator — ``acquire``/``release``
    run on the engine scheduler thread only."""

    def __init__(self, cfg: DecoderConfig, *, max_adapters: int,
                 rank: int, targets: Sequence[str] = ("wq", "wv"),
                 dtype=None):
        if max_adapters < 1:
            raise ValueError("max_adapters must be >= 1")
        for t in targets:
            target_dims(cfg, t)                   # validates the name
        self.cfg = cfg
        self.max_adapters = int(max_adapters)
        self.rank = int(rank)
        self.targets = tuple(targets)
        dt = cfg.activation_dtype if dtype is None else dtype
        self._lock = threading.Lock()
        self._specs: dict[str, AdapterSpec] = {}   # guarded_by: _lock
        # Slot state below: lockfree: scheduler-confined
        self._slot_of: dict[str, int] = {}
        self._name_of: list[Optional[str]] = [None] * self.max_adapters
        self._lru: "OrderedDict[str, None]" = OrderedDict()
        self._refs: dict[str, int] = {}
        self._stamps: dict[str, list[str]] = {}
        self.last_hot_load: Optional[str] = None  # lockfree: scheduler-confined
        self.stats = {"acquires": 0, "hits": 0, "loads": 0,  # lockfree: scheduler-confined
                      "evictions": 0}
        from kubeflow_tpu.runtime.sanitize import enabled

        self.refcount_debug = enabled("refcount")
        L = cfg.n_layers
        S = self.max_adapters
        self.buffers: dict[str, Any] = {  # lockfree: scheduler-confined
            "scale": jnp.zeros((S,), jnp.float32),
            "targets": {},
        }
        for t in self.targets:
            din, dout = target_dims(cfg, t)
            self.buffers["targets"][t] = (
                jnp.zeros((L, S, din, self.rank), dt),
                jnp.zeros((L, S, self.rank, dout), dt))
        self._upload = jax.jit(_upload_slot, donate_argnums=(0,))

    # -- registration (thread-safe) ----------------------------------------

    def register(self, spec: AdapterSpec) -> AdapterSpec:
        if spec.rank < 1 or spec.rank > self.rank:
            raise ValueError(
                f"adapter {spec.name!r} rank {spec.rank} exceeds the "
                f"engine's packed rank cap {self.rank}")
        with self._lock:
            self._specs[spec.name] = spec
        return spec

    def known(self, name: str) -> bool:
        with self._lock:
            return name in self._specs

    def names(self) -> list[str]:
        with self._lock:
            return list(self._specs)

    def spec(self, name: str) -> AdapterSpec:
        with self._lock:
            return self._specs[name]

    # -- observability -----------------------------------------------------

    def resident(self) -> list[str]:
        """Adapters currently hot in a device slot (the
        ``kftpu_engine_adapters_resident`` series' label set)."""
        return [n for n in self._name_of if n is not None]

    def slot_idx(self, name: str) -> Optional[int]:
        return self._slot_of.get(name)

    def refs(self, name: str) -> int:
        return self._refs.get(name, 0)

    def pending_pressure(self) -> bool:
        """True when every slot is referenced — an arriving new tenant
        cannot hot-load until something drains. The engine folds this
        into the KV-tier pressure signal (HBM headroom is shared)."""
        free = sum(1 for n in self._name_of
                   if n is None or self._refs.get(n, 0) == 0)
        return free == 0

    def packed_bytes(self) -> int:
        total = 0
        for a, b in self.buffers["targets"].values():
            total += a.size * a.dtype.itemsize + b.size * b.dtype.itemsize
        return total

    def snapshot(self) -> dict:
        out = dict(self.stats)
        out["resident"] = len(self._slot_of)
        out["slots"] = self.max_adapters
        return out

    # -- refcount sanitizer -------------------------------------------------

    def _stamp(self, name: str, owner: Optional[str]) -> None:
        from kubeflow_tpu.runtime.sanitize import call_site

        label = owner if owner is not None else call_site((__file__,))
        self._stamps.setdefault(name, []).append(label)

    def _unstamp(self, name: str) -> None:
        stamps = self._stamps.get(name)
        if stamps:
            stamps.pop()
            if not stamps:
                del self._stamps[name]

    def leak_report_by_owner(self) -> dict:
        """owner -> outstanding adapter references (refcount mode; {}
        when quiescent) — the lora chaos suite's per-owner audit."""
        out: dict[str, int] = {}
        for name, n in self._refs.items():
            if n <= 0:
                continue
            for label in self._stamps.get(name, ()) or ["<unstamped>"]:
                out[label] = out.get(label, 0) + 1
        return out

    def assert_quiescent(self) -> None:
        held = {n: r for n, r in self._refs.items() if r > 0}
        if held:
            msg = f"adapter slot leak: {held}"
            if self.refcount_debug:
                msg += ("; outstanding references by owner: "
                        + ", ".join(f"{o}={n}" for o, n in
                                    sorted(self.leak_report_by_owner()
                                           .items())))
            raise AssertionError(msg)

    # -- acquire / release (scheduler thread) -------------------------------

    def acquire(self, name: str, owner: Optional[str] = None
                ) -> tuple[int, bool]:
        """One reference on ``name``'s slot, hot-loading on miss.
        Returns ``(slot_idx, hot_loaded)``. Raises ``KeyError`` for an
        unregistered name (the protocol layers' 404) and
        ``AdapterSlotsExhausted`` when every slot is referenced (the
        engine's admission-backpressure signal)."""
        with self._lock:
            spec = self._specs.get(name)
        if spec is None:
            raise KeyError(f"unknown model {name!r}: adapter not registered")
        self.stats["acquires"] += 1
        hot = False
        slot = self._slot_of.get(name)
        if slot is None:
            slot = self._load_slot(spec)
            hot = True
        else:
            self.stats["hits"] += 1
        self._refs[name] = self._refs.get(name, 0) + 1
        if self.refcount_debug:
            self._stamp(name, owner)
        self._lru.move_to_end(name)
        self.last_hot_load = name if hot else None
        return slot, hot

    def release(self, name: str, owner: Optional[str] = None) -> None:
        n = self._refs.get(name, 0) - 1
        assert n >= 0, f"double release of adapter {name!r}"
        self._refs[name] = n
        if self.refcount_debug:
            self._unstamp(name)

    def _load_slot(self, spec: AdapterSpec) -> int:
        """Place ``spec`` into a free slot, evicting the LRU ref-0
        resident if none is free, and scatter its padded factors into
        the packed buffers (ONE fixed-shape donated dispatch)."""
        slot = None
        for i, n in enumerate(self._name_of):
            if n is None:
                slot = i
                break
        if slot is None:
            victim = next((n for n in self._lru
                           if self._refs.get(n, 0) == 0), None)
            if victim is None:
                raise AdapterSlotsExhausted(
                    f"all {self.max_adapters} adapter slots referenced")
            slot = self._slot_of.pop(victim)
            self._lru.pop(victim, None)
            self._name_of[slot] = None
            self.stats["evictions"] += 1
            logger.info("evicting adapter %s (LRU) from slot %d",
                        victim, slot)
        weights = spec.resolve_weights()
        updates: dict[str, tuple] = {}
        L = self.cfg.n_layers
        dt = self.buffers["targets"][self.targets[0]][0].dtype
        for t in self.targets:
            din, dout = target_dims(self.cfg, t)
            pa = np.zeros((L, din, self.rank), dt)
            pb = np.zeros((L, self.rank, dout), dt)
            ab = weights.get(t)
            if ab is not None:
                a, b = np.asarray(ab[0]), np.asarray(ab[1])
                if a.shape != (L, din, spec.rank) \
                        or b.shape != (L, spec.rank, dout):
                    raise ValueError(
                        f"adapter {spec.name!r} target {t}: shapes "
                        f"{a.shape}/{b.shape} do not match "
                        f"{(L, din, spec.rank)}/{(L, spec.rank, dout)}")
                pa[:, :, :spec.rank] = a
                pb[:, :spec.rank, :] = b
            updates[t] = (jnp.asarray(pa), jnp.asarray(pb))
        self.buffers = self._upload(
            self.buffers, jax.device_put(np.int32(slot)),
            jax.device_put(np.float32(spec.scale)), updates)
        self._slot_of[spec.name] = slot
        self._name_of[slot] = spec.name
        self._lru[spec.name] = None
        self._lru.move_to_end(spec.name)
        self.stats["loads"] += 1
        return slot
