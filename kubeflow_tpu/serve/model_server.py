"""The ``model_server`` worker entrypoint: what an InferenceService predictor
replica runs (≈ the kserve-container + storage-initializer pair in one
process — SURVEY.md §3.2 data path).

Config (injected by the ISVC controller into WorkloadSpec.config):
    model:     {"preset": str, "overrides": {...}}  decoder architecture
    storage_uri: str | None                         weights source
    batching:  BatchingSpec fields                  engine knobs
    port:      int                                  HTTP port (pre-assigned)
    service:   str                                  exposed model name
"""

from __future__ import annotations

import time
from typing import Callable

from kubeflow_tpu.runtime.entrypoints import WorkerContext, register_entrypoint

#: Named transformer handlers (the "registered name" form of
#: TransformerSpec.handler; the alternative is "module:function").
transformer_registry: dict[str, Callable] = {}


def register_transformer(name: str):
    def deco(fn: Callable) -> Callable:
        transformer_registry[name] = fn
        return fn
    return deco


def resolve_transformer(handler: str) -> Callable:
    if handler in transformer_registry:
        return transformer_registry[handler]
    module, sep, attr = handler.partition(":")
    if not sep:
        raise KeyError(
            f"transformer {handler!r} is not registered and is not a "
            f"'module:function' path; registered: "
            f"{sorted(transformer_registry)}")
    import importlib

    return getattr(importlib.import_module(module), attr)


@register_entrypoint("model_server")
def model_server(ctx: WorkerContext) -> int:
    from kubeflow_tpu.core.serving import BatchingSpec
    from kubeflow_tpu.models.config import preset
    from kubeflow_tpu.runtime.bootstrap import apply_platform
    from kubeflow_tpu.serve.engine import LLMEngine
    from kubeflow_tpu.serve.server import ModelServer
    from kubeflow_tpu.serve.storage import load_params

    # Single-replica servers take bootstrap's light-start path (no mesh),
    # so the worker's platform selection must apply here, BEFORE
    # load_params initializes JAX — a platform="cpu" replica must never
    # grab the hardware backend.
    apply_platform(ctx.env)
    conf = ctx.config
    model_conf = conf.get("model", {})
    cfg = preset(model_conf.get("preset", "tiny"),
                 **model_conf.get("overrides", {}))
    params = load_params(conf.get("storage_uri"), cfg)
    batching = BatchingSpec(**conf.get("batching", {}))
    # ctx.mesh is non-None when the predictor requested tensor parallelism
    # (PredictorSpec.parallelism → WorkerSpec.parallelism → bootstrap): the
    # engine shards weights + KV over it — one replica process, N chips.
    engine = LLMEngine(cfg, batching, params=params, mesh=ctx.mesh)
    transformer = None
    t_conf = conf.get("transformer")
    if t_conf:
        # kserve-transformer analog: fn(text, phase, **config).
        import functools

        fn = resolve_transformer(t_conf["handler"])
        if t_conf.get("config"):
            fn = functools.partial(fn, **t_conf["config"])
        transformer = fn
    from kubeflow_tpu.serve.explain import build_explainer

    server = ModelServer(conf.get("service", "model"), engine,
                         transformer=transformer,
                         explainer=build_explainer(conf.get("explainer")),
                         port=int(conf["port"]))
    server.start()
    try:
        while True:          # serve until SIGTERM (exit 143 via worker_main)
            time.sleep(0.5)
    finally:
        server.stop()
    return 0
